"""Cache-protocol contract checker (ISSUE 15 tentpole, static half).

The engine's most expensive recurring bug class is the cache TOCTOU:
PR 4's stale scan-cache insert, PR 8's plan-cache write-epoch veto,
PR 12's result-cache partial-hit double-apply — each a protocol rule
that existed only in review comments until it was violated. This
checker turns the protocol into a DECLARED registry: every engine
cache is listed in :data:`SPECS` with the contract clauses it must
satisfy, and pure-AST passes verify each clause against the live tree.
A new cache that doesn't declare itself here is caught too (see
``undeclared-cache``): any class assigning ``self._entries`` under a
lock in a scanned module must appear in the registry.

Contract clauses (each a rule with a red fixture under
tests/fixtures/analyze_bad/):

- ``cache-plain-lock`` — the cache's lock attribute must be built by
  ``checked_lock``/``checked_rlock`` so it enters the runtime
  lock-order graph and the guarded-field validator
  (presto_tpu/_devtools/lockcheck.py).
- ``cache-key-missing-version`` — a cache declaring ``versions="key"``
  must reference its data-version parameter inside the declared key
  builder (the scan cache's contract: a write changes the key, so
  stale entries are unreachable, not wrong).
- ``cache-missing-version-recheck`` — a ``versions="key"`` insert must
  re-read ``data_version`` under the cache lock (PR 4's fix: a write
  landing mid-decode already invalidated, so inserting under the stale
  key would squat reserved bytes forever).
- ``cache-missing-deps`` — a cache declaring ``versions="deps"`` must
  read ``data_version`` in its dep builder AND in its hit-path
  revalidation (the plan/result cache contract: entries stamp dep
  versions and every hit re-checks them).
- ``cache-missing-epoch-veto`` — every declared insert/re-stamp method
  must compare the caller's epoch against ``self._epoch`` INSIDE a
  ``with self._lock:`` block (PR 8's fix: a connector write notifying
  mid-plan bumps the epoch and the insert must refuse).
- ``cache-epoch-after-deps`` — every declared orchestration function
  must capture the write epoch LEXICALLY BEFORE its first call into
  the dep-snapshot/plan builder (PR 12 round-2 fix: deps-then-epoch
  stamps pre-write versions on a post-write epoch and the next partial
  hit double-applies).
- ``cache-missing-invalidation-hook`` — the cache's module must
  register an eager-invalidation listener via ``spi.on_data_change``
  whose handler reaches the cache's ``invalidate``.
- ``cache-unbounded`` — the insert path must bound residency: either
  byte accounting against a ``QueryMemoryPool`` (reserve/evict) or an
  entry-cap eviction loop (``popitem``/LRU shrink).
- ``connector-write-no-notify`` — every write method of a versioned
  connector (one that defines ``data_version``) must reach
  ``spi.notify_data_change`` directly or through a same-class helper
  chain (``_data_changed``/``_note_write``-style); a write path that
  skips it leaves every engine cache serving deleted data.

Distributed clauses (ISSUE 19: the fleet's broadcast-fold surface,
presto_tpu/serving/fleet.py — remote write bumps folded into local
caches):

- ``fleet-fold-unaudited`` — every declared fold function must reach
  ``spi.notify_data_change`` (the audited re-entry point): folding a
  remote bump through the spi path runs every cache's registered
  ``_on_write`` listener (note_write epoch bump, then invalidate), so
  the epoch-before-deps veto covers remote writes exactly like local
  ones.
- ``fleet-fold-bypass`` — the fleet module must never call a cache's
  ``invalidate``/``note_write`` directly; a direct poke skips the
  other caches' listeners and the lock/epoch discipline the audited
  path carries.
- ``fleet-fold-seq-order`` — inside a fold function, the
  ``notify_data_change`` call must come LEXICALLY BEFORE the dedupe
  high-water store (``self._seen[...] = seq``): seq-then-notify marks
  the bump delivered before the caches heard it, so a fold that dies
  mid-way is deduped away on retry and the remote write is never
  applied (the broadcast-fold form of epoch-before-deps).

Like every checker in this package: no engine import, stable idents
(``caches:rule:path:symbol``), findings suppressed only via the
committed (empty) baseline.
"""
from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .base import (Finding, add_parents, ancestors, dotted,
                   parse_file, rel, str_const, walk_py)

CHECKER = "caches"

_CHECKED_CTORS = {"checked_lock", "checked_rlock"}


@dataclasses.dataclass(frozen=True)
class CacheSpec:
    """One declared engine cache and the contract clauses that apply.

    ``versions`` is how staleness is kept out: ``"key"`` (the data
    version is a key component — scan cache), ``"deps"`` (entries stamp
    dep versions revalidated per hit — plan/template/result caches) or
    ``"pure"`` (output is a pure function of the key — parse cache).
    ``orchestrations`` maps function name -> tuple of dep/plan-builder
    callee names whose first call must come lexically after the
    ``.epoch()`` capture."""
    name: str
    module: str                              # repo-relative path
    cache_class: Optional[str] = None        # None: module-level dict LRU
    lock_attrs: Tuple[str, ...] = ("_lock",)
    versions: str = "deps"                   # key | deps | pure
    key_fn: Optional[str] = None             # versions=key: builder name
    key_version_param: str = "version"
    version_recheck_in: Tuple[str, ...] = ()
    deps_fns: Tuple[str, ...] = ()           # versions=deps: builders
    revalidate_fns: Tuple[str, ...] = ()     # versions=deps: hit path
    epoch_veto_in: Tuple[str, ...] = ()      # methods comparing _epoch
    orchestrations: Dict[str, Tuple[str, ...]] = \
        dataclasses.field(default_factory=dict)
    invalidation_hook: bool = True
    bounded_in: Tuple[str, ...] = ()         # insert/shrink methods
    inherits: Optional[str] = None           # contract lives on this spec


#: the engine's cache registry — ADD NEW CACHES HERE (the
#: undeclared-cache rule fails otherwise) and docs/static_analysis.md
#: documents each clause.
SPECS: Tuple[CacheSpec, ...] = (
    CacheSpec(
        name="scancache",
        module="presto_tpu/exec/scancache.py",
        cache_class="ScanCache",
        versions="key",
        key_fn="key",
        key_version_param="version",
        version_recheck_in=("put",),
        epoch_veto_in=(),              # version-in-key makes the epoch
        orchestrations={},             # window a key miss instead
        bounded_in=("put",),
    ),
    CacheSpec(
        name="plancache",
        module="presto_tpu/serving/plancache.py",
        cache_class="PlanCache",
        versions="deps",
        deps_fns=("_plan_deps",),
        revalidate_fns=("_dep_live",),
        epoch_veto_in=("put",),
        orchestrations={"cached_plan": ("optimize", "plan_query")},
        bounded_in=("put",),
    ),
    CacheSpec(
        name="templates",
        module="presto_tpu/serving/template.py",
        cache_class=None,              # an instance of PlanCache
        inherits="plancache",
        versions="deps",
        orchestrations={"template_plan": ("optimize", "plan_query")},
        bounded_in=(),
    ),
    CacheSpec(
        name="resultcache",
        module="presto_tpu/serving/resultcache.py",
        cache_class="ResultCache",
        versions="deps",
        deps_fns=("plan_deps",),
        revalidate_fns=("get",),
        epoch_veto_in=("put", "update"),
        orchestrations={"begin": ("plan_deps",)},
        bounded_in=("put", "_account_locked", "_shrink_locked"),
    ),
    CacheSpec(
        name="parsecache",
        module="presto_tpu/serving/plancache.py",
        cache_class=None,              # module-level dict LRU
        lock_attrs=("_stmt_lock",),
        versions="pure",               # parse(text) is a pure function
        invalidation_hook=False,
        bounded_in=("parse_cached",),
    ),
    CacheSpec(
        name="identmemo",
        module="presto_tpu/serving/plancache.py",
        cache_class="IdentMemo",
        versions="pure",               # value derived from pinned key
        invalidation_hook=False,
        bounded_in=("get",),
    ),
)

#: connector write-surface method names checked for the notify rule
WRITE_METHODS = ("create_table", "drop_table", "append", "delete",
                 "insert", "truncate", "transaction_restore")

CONNECTOR_SCOPE = ("presto_tpu/connectors",)


# -- per-module AST facts -----------------------------------------------------

class _Mod:
    def __init__(self, path: str, rpath: str):
        self.path = path
        self.rpath = rpath
        self.tree = parse_file(path)
        if self.tree is not None:
            add_parents(self.tree)

    def cls(self, name: str) -> Optional[ast.ClassDef]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef) and node.name == name:
                return node
        return None

    def fn(self, name: str, under: Optional[ast.AST] = None
           ) -> Optional[ast.FunctionDef]:
        scope = under if under is not None else self.tree
        for node in ast.walk(scope):
            if isinstance(node, ast.FunctionDef) and node.name == name:
                return node
        return None


def _calls_in(scope: ast.AST) -> List[ast.Call]:
    return [n for n in ast.walk(scope) if isinstance(n, ast.Call)]


def _call_tail(call: ast.Call) -> str:
    return (dotted(call.func) or "").split(".")[-1]


def _under_self_lock(node: ast.AST, lock_attrs: Sequence[str]) -> bool:
    """Is ``node`` lexically inside ``with self.<lock>:`` (or a
    module-level ``with <lock>:``) for one of the declared lock
    attributes?"""
    for anc in ancestors(node):
        if not isinstance(anc, ast.With):
            continue
        for item in anc.items:
            d = dotted(item.context_expr) or ""
            tail = d.split(".")[-1]
            if tail in lock_attrs:
                return True
    return False


def _lock_assignments(scope: ast.AST, lock_attrs: Sequence[str]
                      ) -> List[Tuple[str, Optional[str], int]]:
    """[(attr, ctor_tail or None, lineno)] for every assignment of a
    declared lock attribute anywhere under ``scope`` (self.X = ... or
    module-level X = ...)."""
    out = []
    for node in ast.walk(scope):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            d = dotted(tgt) or ""
            tail = d.split(".")[-1]
            if tail not in lock_attrs:
                continue
            ctor = None
            if isinstance(node.value, ast.Call):
                ctor = _call_tail(node.value)
            out.append((tail, ctor, node.lineno))
    return out


# -- clause checks ------------------------------------------------------------

def _check_lock(spec: CacheSpec, mod: _Mod, scope: ast.AST
                ) -> List[Finding]:
    out: List[Finding] = []
    assigns = _lock_assignments(scope, spec.lock_attrs)
    if not assigns:
        out.append(Finding(
            CHECKER, "cache-plain-lock", mod.rpath, 1, spec.name,
            f"cache {spec.name!r}: no assignment of lock attribute(s) "
            f"{spec.lock_attrs} found — the contract needs a "
            f"checked_lock the runtime validator can see"))
        return out
    for attr, ctor, lineno in assigns:
        if ctor not in _CHECKED_CTORS:
            out.append(Finding(
                CHECKER, "cache-plain-lock", mod.rpath, lineno,
                f"{spec.name}.{attr}",
                f"cache {spec.name!r} lock {attr!r} is built by "
                f"{ctor or 'a non-call'} — must be checked_lock/"
                f"checked_rlock so it enters the runtime lock graph "
                f"and guarded-field validation"))
    return out


def _check_key_versions(spec: CacheSpec, mod: _Mod, scope: ast.AST
                        ) -> List[Finding]:
    out: List[Finding] = []
    fn = mod.fn(spec.key_fn, under=scope)
    if fn is None:
        out.append(Finding(
            CHECKER, "cache-key-missing-version", mod.rpath, 1,
            f"{spec.name}.{spec.key_fn}",
            f"declared key builder {spec.key_fn!r} not found"))
        return out
    param = spec.key_version_param
    params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
    used = any(isinstance(n, ast.Name) and n.id == param
               and isinstance(n.ctx, ast.Load) for n in ast.walk(fn))
    if param not in params or not used:
        out.append(Finding(
            CHECKER, "cache-key-missing-version", mod.rpath, fn.lineno,
            f"{spec.name}.{spec.key_fn}",
            f"key builder {spec.key_fn!r} must take and use a "
            f"{param!r} component — without the data version in the "
            f"key, a connector write leaves stale entries reachable"))
    # insert-time recheck under the lock
    for meth in spec.version_recheck_in:
        m = mod.fn(meth, under=scope)
        if m is None:
            out.append(Finding(
                CHECKER, "cache-missing-version-recheck", mod.rpath, 1,
                f"{spec.name}.{meth}",
                f"declared insert method {meth!r} not found"))
            continue
        ok = any(_call_tail(c) == "data_version"
                 or (isinstance(c.func, ast.Name)
                     and c.func.id == "getattr" and len(c.args) >= 2
                     and str_const(c.args[1]) == "data_version")
                 for c in _calls_in(m)
                 if _under_self_lock(c, spec.lock_attrs))
        if not ok:
            out.append(Finding(
                CHECKER, "cache-missing-version-recheck", mod.rpath,
                m.lineno, f"{spec.name}.{meth}",
                f"{meth!r} must re-read data_version under the cache "
                f"lock before inserting (PR 4 contract: a write that "
                f"landed mid-decode already invalidated; a stale "
                f"insert squats reserved bytes forever)"))
    return out


def _reads_data_version(fn: ast.FunctionDef) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Attribute) and n.attr == "data_version":
            return True
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "getattr" and len(n.args) >= 2 \
                and str_const(n.args[1]) == "data_version":
            return True
        if isinstance(n, ast.Call):
            tail = _call_tail(n)
            if tail in ("_dep_live", "_plan_deps", "plan_deps"):
                return True            # delegation to a dep helper
    return False


def _check_dep_versions(spec: CacheSpec, mod: _Mod, scope: ast.AST
                        ) -> List[Finding]:
    out: List[Finding] = []
    for kind, names in (("dep builder", spec.deps_fns),
                        ("hit revalidation", spec.revalidate_fns)):
        for name in names:
            fn = mod.fn(name, under=scope) or mod.fn(name)
            if fn is None:
                out.append(Finding(
                    CHECKER, "cache-missing-deps", mod.rpath, 1,
                    f"{spec.name}.{name}",
                    f"declared {kind} {name!r} not found"))
                continue
            if not _reads_data_version(fn):
                out.append(Finding(
                    CHECKER, "cache-missing-deps", mod.rpath,
                    fn.lineno, f"{spec.name}.{name}",
                    f"{kind} {name!r} never reads data_version — "
                    f"entries would stamp nothing and hits would "
                    f"never notice a write"))
    return out


def _check_epoch_veto(spec: CacheSpec, mod: _Mod, scope: ast.AST
                      ) -> List[Finding]:
    out: List[Finding] = []
    for meth in spec.epoch_veto_in:
        m = mod.fn(meth, under=scope)
        if m is None:
            out.append(Finding(
                CHECKER, "cache-missing-epoch-veto", mod.rpath, 1,
                f"{spec.name}.{meth}",
                f"declared insert/re-stamp method {meth!r} not found"))
            continue
        ok = False
        for n in ast.walk(m):
            if not isinstance(n, ast.Compare):
                continue
            sides = [n.left] + list(n.comparators)
            if any(isinstance(s, ast.Attribute) and s.attr == "_epoch"
                   for s in sides) \
                    and _under_self_lock(n, spec.lock_attrs):
                ok = True
                break
        if not ok:
            out.append(Finding(
                CHECKER, "cache-missing-epoch-veto", mod.rpath,
                m.lineno, f"{spec.name}.{meth}",
                f"{meth!r} must compare the caller's captured epoch "
                f"against self._epoch under the cache lock — a "
                f"connector write notifying mid-window must veto the "
                f"insert (PR 8 plan-cache TOCTOU)"))
    return out


def _check_epoch_order(spec: CacheSpec, mod: _Mod) -> List[Finding]:
    out: List[Finding] = []
    for fn_name, builders in spec.orchestrations.items():
        fn = mod.fn(fn_name)
        if fn is None:
            out.append(Finding(
                CHECKER, "cache-epoch-after-deps", mod.rpath, 1,
                f"{spec.name}.{fn_name}",
                f"declared orchestration {fn_name!r} not found"))
            continue
        epoch_line = None
        builder_line = None
        for c in _calls_in(fn):
            tail = _call_tail(c)
            if tail == "epoch" and epoch_line is None:
                epoch_line = c.lineno
            if tail in builders:
                # the LAST builder call is the one whose product the
                # insert stamps (earlier calls are cache-off early
                # returns that never insert)
                builder_line = max(builder_line or 0, c.lineno)
        if epoch_line is None:
            out.append(Finding(
                CHECKER, "cache-epoch-after-deps", mod.rpath,
                fn.lineno, f"{spec.name}.{fn_name}",
                f"{fn_name!r} never captures the write epoch "
                f"(.epoch()) before building deps — a mid-window "
                f"write cannot veto its insert"))
        elif builder_line is not None and epoch_line > builder_line:
            out.append(Finding(
                CHECKER, "cache-epoch-after-deps", mod.rpath,
                epoch_line, f"{spec.name}.{fn_name}",
                f"{fn_name!r} captures the write epoch AFTER calling "
                f"{builders} — deps-then-epoch stamps pre-write "
                f"versions on a post-write epoch, and the next "
                f"incremental hit double-applies (PR 12 round-2 fix)"))
    return out


def _check_invalidation_hook(spec: CacheSpec, mod: _Mod) -> List[Finding]:
    handlers: Set[str] = set()
    registered_inline = False
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if _call_tail(node) != "on_data_change" or not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Name):
            handlers.add(arg.id)
        elif isinstance(arg, ast.Lambda):
            if any(isinstance(n, ast.Attribute)
                   and n.attr in ("invalidate", "note_write")
                   for n in ast.walk(arg)):
                registered_inline = True
    if registered_inline:
        return []
    for name in handlers:
        fn = mod.fn(name)
        if fn is not None and any(
                isinstance(n, ast.Attribute)
                and n.attr in ("invalidate", "note_write")
                for n in ast.walk(fn)):
            return []
    return [Finding(
        CHECKER, "cache-missing-invalidation-hook", mod.rpath, 1,
        spec.name,
        f"cache {spec.name!r}'s module never registers an "
        f"spi.on_data_change handler reaching invalidate/note_write — "
        f"connector writes would only be noticed by per-hit "
        f"revalidation, leaving the write-epoch veto unarmed")]


def _check_bounded(spec: CacheSpec, mod: _Mod, scope: ast.AST
                   ) -> List[Finding]:
    if not spec.bounded_in:
        return []
    for meth in spec.bounded_in:
        m = mod.fn(meth, under=scope) or mod.fn(meth)
        if m is None:
            continue
        for c in _calls_in(m):
            tail = _call_tail(c)
            if tail in ("popitem", "_evict_lru", "_shrink_locked",
                        "try_reserve", "reserve"):
                return []
    return [Finding(
        CHECKER, "cache-unbounded", mod.rpath, 1, spec.name,
        f"cache {spec.name!r}: none of {spec.bounded_in} bounds "
        f"residency (no pool reserve/evict, no entry-cap popitem) — "
        f"every cache must account bytes or cap entries with "
        f"observable eviction")]


# -- undeclared caches --------------------------------------------------------

#: modules swept for cache-shaped classes that skipped the registry
SWEEP_SCOPE = ("presto_tpu/exec/scancache.py", "presto_tpu/serving")


def _undeclared_findings(root: str, specs: Sequence[CacheSpec],
                         scan_paths: Optional[Sequence[str]] = None
                         ) -> List[Finding]:
    declared = {(s.module, s.cache_class) for s in specs
                if s.cache_class}
    out: List[Finding] = []
    paths = (list(scan_paths) if scan_paths is not None
             else walk_py(root, SWEEP_SCOPE))
    for path in paths:
        rpath = rel(path, root)
        mod = _Mod(path, rpath)
        if mod.tree is None:
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            has_entries = False
            for n in ast.walk(node):
                if isinstance(n, ast.Assign):
                    targets = n.targets
                elif isinstance(n, ast.AnnAssign):
                    targets = [n.target]
                else:
                    continue
                if any((dotted(t) or "").endswith("._entries")
                       for t in targets):
                    has_entries = True
                    break
            if has_entries and (rpath, node.name) not in declared:
                out.append(Finding(
                    CHECKER, "undeclared-cache", rpath, node.lineno,
                    node.name,
                    f"class {node.name!r} looks like an engine cache "
                    f"(assigns self._entries) but is not declared in "
                    f"tools/analyze/caches.SPECS — declare it with "
                    f"its contract clauses"))
    return out


# -- connector write rule -----------------------------------------------------

def connector_findings(root: str,
                       scan_paths: Optional[Sequence[str]] = None
                       ) -> List[Finding]:
    paths = (list(scan_paths) if scan_paths is not None
             else sorted(set(walk_py(root, CONNECTOR_SCOPE))))
    out: List[Finding] = []
    for path in paths:
        rpath = rel(path, root)
        mod = _Mod(path, rpath)
        if mod.tree is None:
            out.append(Finding(CHECKER, "parse-error", rpath, 1,
                               "<module>", "file does not parse"))
            continue
        for cls in [n for n in ast.walk(mod.tree)
                    if isinstance(n, ast.ClassDef)]:
            methods = {n.name: n for n in cls.body
                       if isinstance(n, ast.FunctionDef)}
            if "data_version" not in methods:
                continue               # unversioned: out of contract
            # helpers that notify (one-hop call-through)
            notifiers = {name for name, m in methods.items()
                         if any(_call_tail(c) == "notify_data_change"
                                for c in _calls_in(m))}
            # transitive same-class call-through (sqlite's write path
            # is create_table -> _invalidate -> _note_write -> notify)
            reaches = set(notifiers)
            changed = True
            while changed:
                changed = False
                for name, m in methods.items():
                    if name not in reaches and any(
                            _call_tail(c) in reaches
                            for c in _calls_in(m)):
                        reaches.add(name)
                        changed = True
            for wname in WRITE_METHODS:
                m = methods.get(wname)
                if m is None or wname in reaches:
                    continue
                out.append(Finding(
                    CHECKER, "connector-write-no-notify", rpath,
                    m.lineno, f"{cls.name}.{wname}",
                    f"versioned connector write path "
                    f"{cls.name}.{wname} never reaches "
                    f"spi.notify_data_change — every engine cache "
                    f"(scan/plan/template/result) would keep serving "
                    f"pre-write data"))
    return out


# -- distributed fold rules (ISSUE 19: serving/fleet.py) ----------------------

#: the fleet-membership module whose fold surface is under contract
FLEET_MODULE = "presto_tpu/serving/fleet.py"
#: functions folding REMOTE write bumps into the local caches
FLEET_FOLD_FNS = ("fold_bump",)
#: the dedupe high-water attribute a fold may only advance post-notify
FLEET_SEEN_ATTR = "_seen"


def fleet_findings(root: str, module: str = FLEET_MODULE,
                   fold_fns: Sequence[str] = FLEET_FOLD_FNS
                   ) -> List[Finding]:
    """The broadcast-fold contract: remote bumps re-enter caches only
    through the audited spi path, and only record delivery after it."""
    path = os.path.join(root, module)
    if not os.path.isfile(path):
        return [Finding(
            CHECKER, "cache-missing-module", module, 1, "fleet",
            f"declared fleet module {module!r} missing")]
    mod = _Mod(path, rel(path, root))
    if mod.tree is None:
        return [Finding(CHECKER, "parse-error", mod.rpath, 1,
                        "<module>", "file does not parse")]
    out: List[Finding] = []
    # fleet-fold-bypass: the module as a whole never pokes caches
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) \
                and node.func.attr in ("invalidate", "note_write"):
            out.append(Finding(
                CHECKER, "fleet-fold-bypass", mod.rpath, node.lineno,
                dotted(node.func) or node.func.attr,
                f"fleet module calls .{node.func.attr}() directly — "
                f"remote bumps must reach caches ONLY through "
                f"spi.notify_data_change so every registered listener "
                f"runs its audited note_write+invalidate sequence"))
    for name in fold_fns:
        fn = mod.fn(name)
        if fn is None:
            out.append(Finding(
                CHECKER, "fleet-fold-unaudited", mod.rpath, 1,
                f"fleet.{name}",
                f"declared fold function {name!r} not found"))
            continue
        notify_line = None
        for c in _calls_in(fn):
            if _call_tail(c) == "notify_data_change":
                notify_line = c.lineno if notify_line is None \
                    else min(notify_line, c.lineno)
        if notify_line is None:
            out.append(Finding(
                CHECKER, "fleet-fold-unaudited", mod.rpath, fn.lineno,
                f"fleet.{name}",
                f"fold function {name!r} never calls "
                f"spi.notify_data_change — a remote write bump that "
                f"skips the audited path leaves local caches (and the "
                f"epoch veto) blind to the write"))
            continue
        seen_store = None
        for n in ast.walk(fn):
            targets = n.targets if isinstance(n, ast.Assign) else (
                [n.target] if isinstance(n, (ast.AnnAssign, ast.AugAssign))
                else ())
            for t in targets:
                if isinstance(t, ast.Subscript) and (
                        dotted(t.value) or "").endswith(
                        f".{FLEET_SEEN_ATTR}"):
                    seen_store = n.lineno if seen_store is None \
                        else min(seen_store, n.lineno)
        if seen_store is None:
            out.append(Finding(
                CHECKER, "fleet-fold-seq-order", mod.rpath, fn.lineno,
                f"fleet.{name}",
                f"fold function {name!r} never advances the dedupe "
                f"high-water mark (self.{FLEET_SEEN_ATTR}[...] = seq) "
                f"— without it every re-delivered bump re-folds and "
                f"the monotonic-delivery contract is gone"))
        elif seen_store < notify_line:
            out.append(Finding(
                CHECKER, "fleet-fold-seq-order", mod.rpath, seen_store,
                f"fleet.{name}",
                f"fold function {name!r} stores the dedupe seq "
                f"(line {seen_store}) BEFORE notify_data_change "
                f"(line {notify_line}) — a fold that dies between the "
                f"two is recorded as delivered and the remote write "
                f"is never applied (seq store must follow the notify)"))
    return out


# -- entry points -------------------------------------------------------------

def check_specs(specs: Sequence[CacheSpec], root: str) -> List[Finding]:
    out: List[Finding] = []
    # inherits= resolves against the FULL registry, not just the specs
    # under check: a --changed run scoped to template.py alone must
    # still see that 'templates' delegates its lock/dep/veto clauses
    # to 'plancache' instead of re-checking them against template.py
    by_name = {s.name: s for s in SPECS}
    by_name.update({s.name: s for s in specs})
    for spec in specs:
        path = os.path.join(root, spec.module)
        if not os.path.isfile(path):
            out.append(Finding(
                CHECKER, "cache-missing-module", spec.module, 1,
                spec.name, f"declared module {spec.module!r} missing"))
            continue
        mod = _Mod(path, rel(path, root))
        if mod.tree is None:
            out.append(Finding(CHECKER, "parse-error", mod.rpath, 1,
                               "<module>", "file does not parse"))
            continue
        scope: ast.AST = mod.tree
        if spec.cache_class:
            cls = mod.cls(spec.cache_class)
            if cls is None:
                out.append(Finding(
                    CHECKER, "cache-missing-module", mod.rpath, 1,
                    spec.name,
                    f"declared class {spec.cache_class!r} not found"))
                continue
            scope = cls
        base = by_name.get(spec.inherits) if spec.inherits else None
        if base is None:
            out.extend(_check_lock(spec, mod, scope))
            if spec.versions == "key":
                out.extend(_check_key_versions(spec, mod, scope))
            elif spec.versions == "deps":
                out.extend(_check_dep_versions(spec, mod, scope))
            out.extend(_check_epoch_veto(spec, mod, scope))
            out.extend(_check_bounded(spec, mod, scope))
        # orchestration + hook clauses always apply to the module that
        # OWNS the instance, inherited machinery or not
        out.extend(_check_epoch_order(spec, mod))
        if spec.invalidation_hook:
            out.extend(_check_invalidation_hook(spec, mod))
    return out


def check(root: str) -> List[Finding]:
    out = check_specs(SPECS, root)
    out.extend(_undeclared_findings(root, SPECS))
    out.extend(connector_findings(root))
    out.extend(fleet_findings(root))
    return out

"""Registry-consistency lints: one framework for every string-keyed
registry where a typo is a silent no-op.

The engine has a family of such registries; each gets the same
treatment —
every literal USE site must resolve to exactly one DECLARATION, every
declaration must be used, and the human-facing doc table must
round-trip against the code:

- **metric families** (``obs/metrics.py`` create-on-first-use):
  naming/type/doc-drift rules, grown from the original
  ``tools/check_metric_names.py`` (now a thin shim over this module).
- **session properties** (``presto_tpu/config.py`` SESSION_PROPERTIES,
  declared via ``_sp(...)``): every ``session.properties.get("...")``/
  ``bool_property(session, "...")``/``properties["..."]`` literal in
  the tree must be declared, every declaration referenced, and the
  table in ``docs/static_analysis.md`` must match two-way.
- **failpoint sites** (``exec/failpoints.py`` SITES): every
  ``FAILPOINTS.hit("...")`` literal must be a declared site, every
  declared site must have a hit() call, and the catalog table in
  ``docs/robustness.md`` must match two-way.
- **alert rules** (``obs/slo.py`` ALERT_RULES): every literal
  ``alert_rule("...")`` must name a declared rule, every declared rule
  must be used, and the "## Alert rules" table in
  ``docs/observability.md`` round-trips two-way — an unknown alert
  name is a page that can never fire.
- **config keys** (``presto_tpu/config.py`` CONFIG_KEYS): literals
  read off parsed ``*.properties`` dicts in config.py / plugin.py /
  connectors must be declared (``session.*``-style prefixes
  supported).
- **environment variables** (``presto_tpu/config.py`` ENV_VARS): every
  ``os.environ.get/[...]/setdefault`` / ``os.getenv`` read of a
  ``PRESTO_TPU_*`` or ``BENCH_*`` name anywhere in the engine, tools,
  or bench must resolve to a declared entry; declared entries must be
  read somewhere; and the table in docs/static_analysis.md round-trips
  two-way like the metric families. An undeclared env knob is the
  worst registry typo: it "works" on the machine that exports it and
  silently does nothing anywhere else.

All checks are AST/regex static — no engine import.
"""
from __future__ import annotations

import ast
import fnmatch
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .base import Finding, dotted, parse_file, rel, str_const, walk_py

CHECKER = "registries"

CONFIG_PY = "presto_tpu/config.py"
FAILPOINTS_PY = "presto_tpu/exec/failpoints.py"
SLO_PY = "presto_tpu/obs/slo.py"
EXPOSITION_PY = "presto_tpu/obs/exposition.py"
OBS_DOC = "docs/observability.md"
ROBUSTNESS_DOC = "docs/robustness.md"
ANALYSIS_DOC = "docs/static_analysis.md"

#: where config-file keys (java.util.Properties style) are read
CONFIG_KEY_SCAN = (CONFIG_PY, "presto_tpu/plugin.py",
                   "presto_tpu/connectors/sqlite.py")


# -- metric families (the check_metric_names.py rules) -----------------------

_METRIC_KINDS = ("counter", "gauge", "histogram")
_SNAKE = re.compile(r"^[a-z][a-z0-9_]*(\*[a-z0-9_]*)*$")
# _ratio is Prometheus's canonical suffix for unitless 0..1 fractions
# (SLO burn rates / error budgets)
_UNIT_SUFFIXES = ("_total", "_seconds", "_bytes", "_ratio")

#: doc tokens that share the unit-suffix shape but are SQL column
#: names, not metric families
_DOC_IGNORE = {"hbm_bytes", "peak_memory_bytes", "output_bytes",
               "arg_bytes", "temp_bytes", "generated_code_bytes",
               "mem_pool_peak_bytes"}

_DOC_FAMILY = re.compile(
    r"^[a-z][a-z0-9_]*_(?:total|seconds|bytes|ratio)$")


def _name_pattern(arg: ast.expr) -> Optional[str]:
    """Metric-name argument as a pattern: literals verbatim, f-string
    interpolations collapsed to ``*``, fully dynamic -> None."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for v in arg.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _check_metric_name(pattern: str) -> Optional[str]:
    family = pattern.split(".", 1)[0]
    if not _SNAKE.match(family.replace("*", "x")):
        return f"{pattern!r}: family {family!r} is not snake_case"
    if not family.endswith(_UNIT_SUFFIXES):
        return (f"{pattern!r}: family {family!r} lacks a unit suffix "
                f"({'/'.join(_UNIT_SUFFIXES)})")
    return None


def metric_sites(path: str) -> Tuple[List[Tuple[str, str, int]], bool]:
    """([(pattern, kind, lineno)], parsed_ok) for counter(/gauge(/
    histogram( calls — a syntax-broken file must FAIL the lint, not be
    silently skipped with its call sites unchecked."""
    tree = parse_file(path)
    if tree is None:
        return [], False
    out: List[Tuple[str, str, int]] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_KINDS and node.args):
            continue
        pattern = _name_pattern(node.args[0])
        if pattern is not None:
            out.append((pattern, node.func.attr, node.lineno))
    return out, True


def exposition_families(path: str) -> Set[str]:
    """Literal ``family("...", ...)`` series the Prometheus exposition
    constructs directly — documented scrape series with no registry
    call site."""
    tree = parse_file(path) if os.path.isfile(path) else None
    if tree is None:
        return set()
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and node.args \
                and isinstance(node.func, ast.Name) \
                and node.func.id == "family":
            pattern = _name_pattern(node.args[0])
            if pattern:
                out.add(pattern)
    return out


def doc_metric_families(doc_path: str) -> Set[str]:
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    out: Set[str] = set()
    for token in re.findall(r"`([^`\n]+)`", text):
        fam = re.split(r"[.{\s(]", token.strip(), maxsplit=1)[0]
        if fam not in _DOC_IGNORE \
                and _DOC_FAMILY.match(fam.replace("*", "x")):
            out.add(fam)
    return out


def metric_findings(src_roots: Sequence[str], root: str,
                    doc_path: Optional[str] = None,
                    exposition_path: Optional[str] = None
                    ) -> List[Finding]:
    findings: List[Finding] = []
    families: Dict[str, Tuple[str, str]] = {}   # family -> (kind, where)
    for path in walk_py(root, [os.path.relpath(r, root)
                               if os.path.isabs(r) else r
                               for r in src_roots]):
        rpath = rel(path, root)
        sites, parsed = metric_sites(path)
        if not parsed:
            findings.append(Finding(
                CHECKER, "parse-error", rpath, 1, "<module>",
                "file does not parse — its metric call sites are "
                "unchecked"))
            continue
        for pattern, kind, lineno in sites:
            bad = _check_metric_name(pattern)
            if bad:
                findings.append(Finding(
                    CHECKER, "bad-metric-name", rpath, lineno,
                    pattern, bad))
                continue
            family = pattern.split(".", 1)[0]
            prev = families.get(family)
            if prev is not None and prev[0] != kind:
                findings.append(Finding(
                    CHECKER, "metric-type-conflict", rpath, lineno,
                    family,
                    f"{family!r} registered as {kind} but as "
                    f"{prev[0]} at {prev[1]}"))
            elif prev is None:
                families[family] = (kind, f"{rpath}:{lineno}")

    if doc_path and os.path.isfile(doc_path):
        expo = exposition_families(
            exposition_path or os.path.join(root, EXPOSITION_PY))
        known = set(families) | expo
        documented = doc_metric_families(doc_path)
        doc_rel = rel(doc_path, root)
        for fam in sorted(documented):
            if not any(fnmatch.fnmatch(fam, pat) or fam == pat
                       for pat in known):
                findings.append(Finding(
                    CHECKER, "metric-doc-drift", doc_rel, 1, fam,
                    f"documents {fam!r} but no such metric family is "
                    f"registered in code"))
        for pat in sorted(families):
            if pat in documented or any(
                    fnmatch.fnmatch(fam, pat) for fam in documented):
                continue
            findings.append(Finding(
                CHECKER, "metric-doc-drift", doc_rel, 1, pat,
                f"metric family {pat!r} is registered in code but not "
                f"documented in {doc_rel}"))
    return findings


# -- doc-table helper --------------------------------------------------------

def doc_table_tokens(doc_path: str, section_marker: str) -> Set[str]:
    """First-cell backticked tokens of the markdown table inside the
    section whose header line starts with ``section_marker``."""
    if not os.path.isfile(doc_path):
        return set()
    out: Set[str] = set()
    in_section = False
    with open(doc_path, encoding="utf-8") as f:
        for line in f:
            if line.startswith("#") and in_section:
                break
            if line.startswith(section_marker):
                in_section = True
                continue
            if in_section and line.lstrip().startswith("|"):
                cells = [c.strip() for c in line.strip().strip("|")
                         .split("|")]
                if cells:
                    m = re.match(r"^`([^`]+)`$", cells[0])
                    if m:
                        out.add(m.group(1))
    return out


# -- session properties ------------------------------------------------------

def declared_session_props(config_path: str) -> Dict[str, int]:
    """name -> lineno of every ``_sp("name", ...)`` declaration."""
    tree = parse_file(config_path)
    out: Dict[str, int] = {}
    if tree is None:
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and node.func.id == "_sp" and node.args:
            name = str_const(node.args[0])
            if name:
                out[name] = node.lineno
    return out


def session_prop_uses(paths: Sequence[str], root: str
                      ) -> List[Tuple[str, str, int]]:
    """[(prop, rpath, lineno)] literal read/write sites:
    ``<x>.properties.get("p")`` / ``<x>.properties["p"]`` (read or
    write) / ``bool_property(s, "p", ...)`` / ``props.get("p")`` where
    ``props`` was assigned from ``<x>.properties`` in the same file."""
    out: List[Tuple[str, str, int]] = []
    for path in paths:
        tree = parse_file(path)
        if tree is None:
            continue
        rpath = rel(path, root)
        #: local aliases of a session-properties dict
        aliases: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Attribute) \
                    and node.value.attr == "properties":
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        aliases.add(tgt.id)
        #: local prop-reader helpers: ``def _int_prop(name, d): ...
        #: session.properties.get(name, d)`` — a call with a literal
        #: first arg is a session-prop use
        readers: Set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.FunctionDef) or not node.args.args:
                continue
            first = node.args.args[0].arg
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and sub.args \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "get" \
                        and isinstance(sub.args[0], ast.Name) \
                        and sub.args[0].id == first:
                    based = dotted(sub.func.value) or ""
                    if based.endswith(".properties") or based in aliases:
                        readers.add(node.name)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and node.args:
                fname = dotted(node.func) or ""
                if fname.split(".")[-1] == "bool_property" \
                        and len(node.args) >= 2:
                    name = str_const(node.args[1])
                    if name:
                        out.append((name, rpath, node.lineno))
                elif fname in readers:
                    name = str_const(node.args[0])
                    if name:
                        out.append((name, rpath, node.lineno))
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("get", "pop"):
                    base = node.func.value
                    based = dotted(base) or ""
                    if based.endswith(".properties") \
                            or based in aliases:
                        name = str_const(node.args[0])
                        if name:
                            out.append((name, rpath, node.lineno))
            elif isinstance(node, ast.Subscript):
                based = dotted(node.value) or ""
                if based.endswith(".properties") or based in aliases:
                    name = str_const(node.slice)
                    if name:
                        out.append((name, rpath, node.lineno))
    return out


def session_prop_findings(root: str,
                          scan_paths: Optional[Sequence[str]] = None,
                          config_path: Optional[str] = None,
                          doc_path: Optional[str] = None,
                          two_way: bool = True
                          ) -> List[Finding]:
    """``two_way=False`` (the --changed fast path) checks only the
    use->declaration direction: a partial scan can prove an unknown
    read, but would falsely report every unscanned declaration as
    unused and every doc row as drift."""
    config_path = config_path or os.path.join(root, CONFIG_PY)
    declared = declared_session_props(config_path)
    paths = (list(scan_paths) if scan_paths is not None
             else sorted(set(walk_py(root, ["presto_tpu"]))))
    uses = session_prop_uses(paths, root)
    out: List[Finding] = []
    used_names: Set[str] = set()
    for name, rpath, line in uses:
        used_names.add(name)
        if name not in declared:
            out.append(Finding(
                CHECKER, "unknown-session-prop", rpath, line, name,
                f"session property {name!r} is read here but never "
                f"declared in config.SESSION_PROPERTIES — the read "
                f"can only ever see its hardcoded default"))
    if not two_way:
        return out
    cfg_rel = rel(config_path, root)
    for name, line in sorted(declared.items()):
        if name not in used_names:
            out.append(Finding(
                CHECKER, "unused-session-prop", cfg_rel, line, name,
                f"session property {name!r} is declared but no code "
                f"reads it — SET SESSION on it silently does nothing"))

    doc = doc_path if doc_path is not None \
        else os.path.join(root, ANALYSIS_DOC)
    if os.path.isfile(doc):
        doc_rel = rel(doc, root)
        documented = doc_table_tokens(doc, "## Session-property")
        for name in sorted(set(declared) - documented):
            out.append(Finding(
                CHECKER, "session-prop-doc-drift", doc_rel, 1, name,
                f"declared session property {name!r} missing from the "
                f"table in {doc_rel}"))
        for name in sorted(documented - set(declared)):
            out.append(Finding(
                CHECKER, "session-prop-doc-drift", doc_rel, 1, name,
                f"{doc_rel} documents unknown session property "
                f"{name!r}"))
    return out


# -- failpoint sites ---------------------------------------------------------

def _module_dict_keys(path: str, var_name: str) -> Dict[str, int]:
    """Literal string keys of a module-level ``VAR = {...}`` (plain or
    annotated assignment) -> lineno."""
    tree = parse_file(path)
    out: Dict[str, int] = {}
    if tree is None:
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign):
            targets, value = [node.target], node.value
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == var_name
                   for t in targets) \
                or not isinstance(value, ast.Dict):
            continue
        for k in value.keys:
            name = str_const(k) if k is not None else None
            if name:
                out[name] = k.lineno
    return out


def declared_failpoint_sites(failpoints_path: str) -> Dict[str, int]:
    """SITES = {"name": ...} keys -> lineno."""
    return _module_dict_keys(failpoints_path, "SITES")


def failpoint_hits(paths: Sequence[str], root: str
                   ) -> List[Tuple[str, str, int]]:
    """[(site, rpath, lineno)] for ``<x>.hit("site", ...)`` calls on a
    FAILPOINTS-ish receiver."""
    out: List[Tuple[str, str, int]] = []
    for path in paths:
        tree = parse_file(path)
        if tree is None:
            continue
        rpath = rel(path, root)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and node.args \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "hit":
                based = (dotted(node.func.value) or "")
                if "FAILPOINTS" not in based.upper():
                    continue
                name = str_const(node.args[0])
                if name:
                    out.append((name, rpath, node.lineno))
    return out


def failpoint_findings(root: str,
                       scan_paths: Optional[Sequence[str]] = None,
                       failpoints_path: Optional[str] = None,
                       doc_path: Optional[str] = None,
                       two_way: bool = True
                       ) -> List[Finding]:
    failpoints_path = failpoints_path \
        or os.path.join(root, FAILPOINTS_PY)
    declared = declared_failpoint_sites(failpoints_path)
    paths = (list(scan_paths) if scan_paths is not None
             else sorted(set(walk_py(root, ["presto_tpu"]))))
    hits = failpoint_hits(paths, root)
    out: List[Finding] = []
    hit_names: Set[str] = set()
    for name, rpath, line in hits:
        hit_names.add(name)
        if name not in declared:
            out.append(Finding(
                CHECKER, "unknown-failpoint-site", rpath, line, name,
                f"FAILPOINTS.hit({name!r}) names a site missing from "
                f"failpoints.SITES — configure() would reject arming "
                f"it, so it can never fire"))
    if not two_way:
        return out
    fp_rel = rel(failpoints_path, root)
    for name, line in sorted(declared.items()):
        if name not in hit_names:
            out.append(Finding(
                CHECKER, "unhit-failpoint-site", fp_rel, line, name,
                f"declared failpoint site {name!r} has no "
                f"FAILPOINTS.hit() call — arming it injects nothing"))

    doc = doc_path if doc_path is not None \
        else os.path.join(root, ROBUSTNESS_DOC)
    if os.path.isfile(doc):
        doc_rel = rel(doc, root)
        documented = doc_table_tokens(doc, "## Failpoint catalog")
        for name in sorted(set(declared) - documented):
            out.append(Finding(
                CHECKER, "failpoint-doc-drift", doc_rel, 1, name,
                f"failpoint site {name!r} missing from the catalog "
                f"table in {doc_rel}"))
        for name in sorted(documented - set(declared)):
            out.append(Finding(
                CHECKER, "failpoint-doc-drift", doc_rel, 1, name,
                f"{doc_rel} catalogs unknown failpoint site {name!r}"))
    return out


# -- alert rules -------------------------------------------------------------

def declared_alert_rules(slo_path: str) -> Dict[str, int]:
    """ALERT_RULES = {"name": ...} keys -> lineno (obs/slo.py)."""
    return _module_dict_keys(slo_path, "ALERT_RULES")


def alert_rule_uses(paths: Sequence[str], root: str
                    ) -> List[Tuple[str, str, int]]:
    """[(rule, rpath, lineno)] for literal ``alert_rule("...")`` calls
    (plain or attribute-qualified)."""
    out: List[Tuple[str, str, int]] = []
    for path in paths:
        tree = parse_file(path)
        if tree is None:
            continue
        rpath = rel(path, root)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None)
            if name != "alert_rule":
                continue
            rule = str_const(node.args[0])
            if rule:
                out.append((rule, rpath, node.lineno))
    return out


def alert_rule_findings(root: str,
                        scan_paths: Optional[Sequence[str]] = None,
                        slo_path: Optional[str] = None,
                        doc_path: Optional[str] = None,
                        two_way: bool = True) -> List[Finding]:
    """Alert-name registry lint, same contract as the failpoint sites:
    every literal ``alert_rule("...")`` must name a declared
    ``ALERT_RULES`` entry (obs/slo.py raises on unknown names at
    runtime, but only when that code path runs — the lint catches the
    typo before a page never fires), every declared rule must have a
    use, and the "## Alert rules" table in docs/observability.md must
    round-trip two-way."""
    slo_path = slo_path or os.path.join(root, SLO_PY)
    declared = declared_alert_rules(slo_path)
    paths = (list(scan_paths) if scan_paths is not None
             else sorted(set(walk_py(root, ["presto_tpu"]))))
    uses = alert_rule_uses(paths, root)
    out: List[Finding] = []
    used: Set[str] = set()
    for rule, rpath, line in uses:
        used.add(rule)
        if rule not in declared:
            out.append(Finding(
                CHECKER, "unknown-alert-rule", rpath, line, rule,
                f"alert_rule({rule!r}) names a rule missing from "
                f"slo.ALERT_RULES — the tracker would raise instead "
                f"of alerting"))
    if not two_way:
        return out
    slo_rel = rel(slo_path, root)
    for rule, line in sorted(declared.items()):
        if rule not in used:
            out.append(Finding(
                CHECKER, "unused-alert-rule", slo_rel, line, rule,
                f"declared alert rule {rule!r} has no alert_rule() "
                f"use — it can never fire"))
    doc = doc_path if doc_path is not None \
        else os.path.join(root, OBS_DOC)
    if os.path.isfile(doc):
        doc_rel = rel(doc, root)
        documented = doc_table_tokens(doc, "## Alert rules")
        for rule in sorted(set(declared) - documented):
            out.append(Finding(
                CHECKER, "alert-rule-doc-drift", doc_rel, 1, rule,
                f"alert rule {rule!r} missing from the Alert rules "
                f"table in {doc_rel}"))
        for rule in sorted(documented - set(declared)):
            out.append(Finding(
                CHECKER, "alert-rule-doc-drift", doc_rel, 1, rule,
                f"{doc_rel} documents unknown alert rule {rule!r}"))
    return out


# -- config keys -------------------------------------------------------------

def declared_config_keys(config_path: str) -> Dict[str, int]:
    """CONFIG_KEYS = {"key-or-glob": "doc"} -> lineno."""
    return _module_dict_keys(config_path, "CONFIG_KEYS")


def config_key_findings(root: str,
                        scan_paths: Optional[Sequence[str]] = None,
                        config_path: Optional[str] = None
                        ) -> List[Finding]:
    config_path = config_path or os.path.join(root, CONFIG_PY)
    declared = declared_config_keys(config_path)
    if not declared:
        return [Finding(CHECKER, "unknown-config-key",
                        rel(config_path, root), 1, "CONFIG_KEYS",
                        "config.py declares no CONFIG_KEYS table")]
    paths = list(scan_paths) if scan_paths is not None else [
        os.path.join(root, p) for p in CONFIG_KEY_SCAN]
    out: List[Finding] = []
    for path in paths:
        tree = parse_file(path) if os.path.isfile(path) else None
        if tree is None:
            continue
        rpath = rel(path, root)
        sites: List[Tuple[str, int]] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and node.args \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" \
                    and (dotted(node.func.value) or "") == "props":
                name = str_const(node.args[0])
                if name:
                    sites.append((name, node.lineno))
            elif isinstance(node, ast.Subscript) \
                    and (dotted(node.value) or "") == "props":
                name = str_const(node.slice)
                if name:
                    sites.append((name, node.lineno))
        for name, line in sites:
            if not any(fnmatch.fnmatch(name, pat) or name == pat
                       for pat in declared):
                out.append(Finding(
                    CHECKER, "unknown-config-key", rpath, line, name,
                    f"config key {name!r} is read here but not "
                    f"declared in config.CONFIG_KEYS"))
    return out


# -- environment variables ---------------------------------------------------

#: reads of names with these prefixes must resolve to an ENV_VARS entry
ENV_ENFORCED_PREFIXES = ("PRESTO_TPU_", "BENCH_")

#: where env vars are read (the production surface; tests may export
#: whatever their harness needs)
ENV_SCAN = ("presto_tpu", "tools", "bench.py", "__graft_entry__.py")


def declared_env_vars(config_path: str) -> Dict[str, int]:
    """ENV_VARS = {"NAME": "doc"} -> lineno."""
    return _module_dict_keys(config_path, "ENV_VARS")


def env_var_reads(paths: Sequence[str], root: str
                  ) -> List[Tuple[str, str, int]]:
    """[(name, rpath, lineno)] for ``os.environ.get("X")`` /
    ``os.environ["X"]`` / ``os.environ.setdefault("X", ...)`` /
    ``os.getenv("X")`` literal sites."""
    out: List[Tuple[str, str, int]] = []
    for path in paths:
        tree = parse_file(path)
        if tree is None:
            continue
        rpath = rel(path, root)
        for node in ast.walk(tree):
            name = None
            if isinstance(node, ast.Call) and node.args:
                fname = dotted(node.func) or ""
                if fname in ("os.getenv", "getenv"):
                    name = str_const(node.args[0])
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("get", "setdefault", "pop") \
                        and (dotted(node.func.value) or "") \
                        .endswith("environ"):
                    name = str_const(node.args[0])
            elif isinstance(node, ast.Subscript) \
                    and (dotted(node.value) or "").endswith("environ"):
                name = str_const(node.slice)
            if name:
                out.append((name, rpath, node.lineno))
    return out


def env_var_findings(root: str,
                     scan_paths: Optional[Sequence[str]] = None,
                     config_path: Optional[str] = None,
                     doc_path: Optional[str] = None,
                     two_way: bool = True) -> List[Finding]:
    config_path = config_path or os.path.join(root, CONFIG_PY)
    declared = declared_env_vars(config_path)
    paths = (list(scan_paths) if scan_paths is not None
             else sorted(set(walk_py(root, ENV_SCAN))))
    reads = env_var_reads(paths, root)
    out: List[Finding] = []
    read_names: Set[str] = set()
    for name, rpath, line in reads:
        read_names.add(name)
        if name.startswith(ENV_ENFORCED_PREFIXES) \
                and name not in declared:
            out.append(Finding(
                CHECKER, "unknown-env-var", rpath, line, name,
                f"environment variable {name!r} is read here but not "
                f"declared in config.ENV_VARS — an exported knob "
                f"nobody can discover, or a typo that silently reads "
                f"nothing"))
    if not two_way:
        return out
    cfg_rel = rel(config_path, root)
    for name, line in sorted(declared.items()):
        if name not in read_names:
            out.append(Finding(
                CHECKER, "unused-env-var", cfg_rel, line, name,
                f"environment variable {name!r} is declared but no "
                f"code reads it — exporting it does nothing"))

    doc = doc_path if doc_path is not None \
        else os.path.join(root, ANALYSIS_DOC)
    if os.path.isfile(doc):
        doc_rel = rel(doc, root)
        documented = doc_table_tokens(doc, "## Environment-variable")
        for name in sorted(set(declared) - documented):
            out.append(Finding(
                CHECKER, "env-var-doc-drift", doc_rel, 1, name,
                f"declared environment variable {name!r} missing from "
                f"the table in {doc_rel}"))
        for name in sorted(documented - set(declared)):
            out.append(Finding(
                CHECKER, "env-var-doc-drift", doc_rel, 1, name,
                f"{doc_rel} documents unknown environment variable "
                f"{name!r}"))
    return out


# -- entry point -------------------------------------------------------------

def check(root: str) -> List[Finding]:
    out: List[Finding] = []
    out.extend(metric_findings(
        ["presto_tpu"], root,
        doc_path=os.path.join(root, OBS_DOC)))
    out.extend(session_prop_findings(root))
    out.extend(failpoint_findings(root))
    out.extend(alert_rule_findings(root))
    out.extend(config_key_findings(root))
    out.extend(env_var_findings(root))
    return out

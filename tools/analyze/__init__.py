"""Engine-aware static-analysis plane (ISSUE 7 tentpole).

Three checker families over the repo, wired into tier-1 via
tests/test_analyze.py and runnable standalone:

    python -m tools.analyze            # exit 0 iff no unsuppressed findings
    python -m tools.analyze --list     # show suppressed findings too

- :mod:`tools.analyze.tracing` — trace-safety (host branches on traced
  values, raw ``jax.jit`` bypassing ops/jitcache, trace-time
  nondeterminism, unbracketed device syncs)
- :mod:`tools.analyze.locks` — lock discipline (static acquisition-
  order cycles, unlocked shared-state writes, unjoined threads); the
  runtime half lives in presto_tpu/_devtools/lockcheck.py
- :mod:`tools.analyze.registries` — string-keyed registry consistency
  (metric families incl. doc drift, session properties, failpoint
  sites, config keys)

Accepted pre-existing findings are suppressed by the committed
``baseline.json`` (see base.py for the ident contract); stale baseline
entries are errors, so fixed findings must drop their suppression in
the same change.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from . import locks, registries, tracing
from .base import REPO, Finding, apply_baseline, load_baseline

CHECKERS = {
    "tracing": tracing.check,
    "locks": locks.check,
    "registries": registries.check,
}

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")


def run(root: Optional[str] = None,
        checkers: Optional[List[str]] = None,
        baseline_path: Optional[str] = None
        ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """-> (unsuppressed findings, suppressed findings, stale baseline
    idents)."""
    root = root or REPO
    findings: List[Finding] = []
    for name in (checkers or sorted(CHECKERS)):
        findings.extend(CHECKERS[name](root))
    baseline: Dict[str, str] = load_baseline(
        BASELINE_PATH if baseline_path is None else baseline_path)
    return apply_baseline(findings, baseline)

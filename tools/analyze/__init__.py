"""Engine-aware static-analysis plane (ISSUE 7 tentpole).

Three checker families over the repo, wired into tier-1 via
tests/test_analyze.py and runnable standalone:

    python -m tools.analyze            # exit 0 iff no unsuppressed findings
    python -m tools.analyze --list     # show suppressed findings too

- :mod:`tools.analyze.tracing` — trace-safety (host branches on traced
  values, raw ``jax.jit`` bypassing ops/jitcache, trace-time
  nondeterminism, unbracketed device syncs)
- :mod:`tools.analyze.locks` — lock discipline (static acquisition-
  order cycles, unlocked shared-state writes, unjoined threads); the
  runtime half lives in presto_tpu/_devtools/lockcheck.py
- :mod:`tools.analyze.registries` — string-keyed registry consistency
  (metric families incl. doc drift, session properties, failpoint
  sites, config keys, PRESTO_TPU_*/BENCH_* environment variables)
- :mod:`tools.analyze.caches` — cache-protocol contracts (the declared
  registry of engine caches: version-keyed or dep-revalidated
  staleness, write-epoch veto under the cache lock, epoch-before-deps
  orchestration order, eager spi.on_data_change invalidation, bounded
  residency, checked locks, connector writes reaching
  notify_data_change); the dynamic halves are
  presto_tpu/_devtools/lockcheck.py (guarded fields) and
  presto_tpu/_devtools/interleave.py (deterministic interleaving
  exploration)

Accepted pre-existing findings are suppressed by the committed
``baseline.json`` (see base.py for the ident contract); stale baseline
entries are errors, so fixed findings must drop their suppression in
the same change.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from . import caches, locks, registries, tracing
from .base import REPO, Finding, apply_baseline, load_baseline

CHECKERS = {
    "tracing": tracing.check,
    "locks": locks.check,
    "registries": registries.check,
    "caches": caches.check,
}

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")


def run(root: Optional[str] = None,
        checkers: Optional[List[str]] = None,
        baseline_path: Optional[str] = None
        ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """-> (unsuppressed findings, suppressed findings, stale baseline
    idents)."""
    root = root or REPO
    findings: List[Finding] = []
    for name in (checkers or sorted(CHECKERS)):
        findings.extend(CHECKERS[name](root))
    baseline: Dict[str, str] = load_baseline(
        BASELINE_PATH if baseline_path is None else baseline_path)
    return apply_baseline(findings, baseline)


#: files whose edit invalidates the GLOBAL registry directions (unused
#: declarations, doc round-trips) — a --changed run that touched one of
#: these falls back to the full scan
_GLOBAL_INPUTS = ("presto_tpu/config.py", "presto_tpu/exec/failpoints.py",
                  "tools/analyze/caches.py",
                  "docs/static_analysis.md", "docs/observability.md",
                  "docs/robustness.md")


def run_changed(files: List[str], root: Optional[str] = None,
                baseline_path: Optional[str] = None
                ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """git-diff-scoped fast mode: per-file rules run only on the
    changed set; registry rules run one-way (use -> declaration) on the
    changed files unless a declaring input changed, in which case the
    full two-way scan runs. Stale-suppression detection is always
    skipped — a partial scan would report every suppression of an
    unscanned file as stale."""
    root = root or REPO
    changed = {f.replace(os.sep, "/") for f in files}
    if any(f in changed for f in _GLOBAL_INPUTS):
        findings, suppressed, _stale = run(
            root=root, baseline_path=baseline_path)
        return findings, suppressed, []

    def scoped(scope) -> List[str]:
        from .base import walk_py
        in_scope = {os.path.relpath(p, root).replace(os.sep, "/"): p
                    for p in walk_py(root, scope)}
        return [in_scope[f] for f in sorted(changed & set(in_scope))]

    findings: List[Finding] = []
    findings.extend(tracing.check_paths(scoped(tracing.SCOPE), root))
    findings.extend(locks.check_paths(scoped(locks.SCOPE), root))
    # cache contracts: only specs whose module changed (inherits=
    # bases resolve against the full registry inside check_specs) +
    # the undeclared-cache sweep over changed sweep-scope files +
    # changed connectors
    specs = [s for s in caches.SPECS if s.module in changed]
    if specs:
        findings.extend(caches.check_specs(specs, root))
    sweep = scoped(caches.SWEEP_SCOPE)
    if sweep:
        findings.extend(caches._undeclared_findings(
            root, caches.SPECS, scan_paths=sweep))
    conn = scoped(caches.CONNECTOR_SCOPE)
    if conn:
        findings.extend(caches.connector_findings(root, scan_paths=conn))
    if caches.FLEET_MODULE in changed:
        findings.extend(caches.fleet_findings(root))
    # registries, use->declaration direction only
    py = scoped(["presto_tpu", "tools", "bench.py",
                 "__graft_entry__.py"])
    if py:
        findings.extend(registries.metric_findings(
            [os.path.relpath(p, root) for p in py
             if "presto_tpu" in p.replace(os.sep, "/")],
            root, doc_path=None))
        findings.extend(registries.session_prop_findings(
            root, scan_paths=py, two_way=False))
        findings.extend(registries.failpoint_findings(
            root, scan_paths=py, two_way=False))
        findings.extend(registries.env_var_findings(
            root, scan_paths=py, two_way=False))
        # config-key reads are only meaningful in the files the full
        # scan covers — `props.get(...)` elsewhere is unrelated dicts
        conf = [p for p in py
                if os.path.relpath(p, root).replace(os.sep, "/")
                in registries.CONFIG_KEY_SCAN]
        if conf:
            findings.extend(registries.config_key_findings(
                root, scan_paths=conf))
    baseline: Dict[str, str] = load_baseline(
        BASELINE_PATH if baseline_path is None else baseline_path)
    keep, dropped, _stale = apply_baseline(findings, baseline)
    return keep, dropped, []


def git_changed_files(root: Optional[str] = None) -> List[str]:
    """Working-tree delta (staged + unstaged + untracked) relative to
    HEAD — the scope of a --changed run."""
    import subprocess
    root = root or REPO
    try:
        out = subprocess.run(
            ["git", "-C", root, "status", "--porcelain"],
            capture_output=True, text=True, timeout=30, check=True
        ).stdout
    except Exception:
        return []
    files: List[str] = []
    for line in out.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip()
        if " -> " in path:                 # rename: take the new side
            path = path.split(" -> ", 1)[1]
        files.append(path.strip('"'))
    return files

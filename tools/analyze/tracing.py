"""Trace-safety linter: host-side sins inside (or around) jitted code.

JAX tracing is our codegen layer (the reference's sql/gen/ bytecode
discipline): a traced function must be a pure shape-polymorphic program.
Four rule families, each a silent-wrongness class no unit test catches
until the shapes change:

- ``tracer-branch`` — host control flow on a traced value inside a
  jitted function (``if``/``while`` on a function arg, ``bool()``/
  ``int()``/``float()``/``.item()`` of one). Under trace these either
  throw ConcretizationTypeError at sf=10's first novel shape bucket or,
  worse, bake one batch's data into the executable.
- ``raw-jit`` — a ``jax.jit``/``pjit`` call site that is not wrapped in
  an ``ops/jitcache._TimedEntry``. Raw entries are invisible to the
  PR 6 profiler (no compile seconds, no device-time attribution, absent
  from system.runtime.executables) and their recompiles are uncapped
  and unobservable.
- ``nondeterminism`` — ``time.*`` / ``random.*`` / ``np.random*``
  calls inside a traced body: they run ONCE at trace time and freeze
  their value into the executable, so "random" is constant per shape
  bucket and replays differ from first runs.
- ``unbracketed-sync`` — ``jax.device_get`` / ``.block_until_ready``
  outside a ``TRACER.span("device-sync", ...)`` (or profiler) scope.
  Async dispatch makes an unbracketed sync a stall nobody can see in
  the trace viewer; the engine's rule since PR 1 is that every
  deliberate device round-trip is a span.
- ``param-bound-read`` — reading ``ir.Param.bound`` (or calling
  ``expr/params.consult``) inside a jitted body. ``.bound`` is the
  BUILD-time literal the template was planned against; under trace it
  bakes that one binding's value into the shared executable, so every
  later binding silently reuses it (the exact staleness the
  parameter-generic plan cache exists to avoid). Dispatch-scope reads
  are the trace-safe channel: ``params.traced_val``/``current_args``
  deliver the LIVE binding as a traced operand — their results are
  tainted like any traced value, so host-branching on them still trips
  ``tracer-branch``; ``consult`` is planner-only (it records template
  reuse guards and must never run under trace).

Taint model (deliberately intraprocedural): the parameters of a jitted
function are traced; names assigned from traced expressions become
traced; structure/shape reads (``is None``, ``len``, ``.shape``,
``.dtype``, ``.ndim``, ``isinstance``) are static under jit and do not
propagate taint. Functions reached only by call from a jitted body are
NOT walked — that keeps false positives near zero at the cost of
missing deep flows, which is the right trade for a gate that must stay
green on every commit.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set

from .base import (Finding, add_parents, ancestors, dotted,
                   enclosing_symbol, parse_file, rel, str_const, walk_py)

CHECKER = "tracing"

#: scope of the walk (ISSUE 7 tentpole) — the traced/offload seams;
#: exec/local.py rides along because its unnest kernel was this
#: checker's first raw-jit catch and the line must hold
SCOPE = ("presto_tpu/ops", "presto_tpu/parallel",
         "presto_tpu/exec/fused.py", "presto_tpu/exec/distributed.py",
         "presto_tpu/exec/local.py", "presto_tpu/exec/local_exchange.py")

#: the one module allowed to call jax.jit directly: it IS the cache
RAW_JIT_ALLOWED_FILES = ("presto_tpu/ops/jitcache.py",)

#: attribute reads that are static under jit (structure, not value)
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "sharding",
                 "weak_type", "columns", "schema", "types", "names"}

#: cast calls that concretize a tracer
_CONCRETIZING_CASTS = {"bool", "int", "float"}

#: nondeterministic call prefixes (host-evaluated at trace time)
_NONDET_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.")

#: expr/params.py dispatch-scope reads: their RESULT is a traced value
#: (the live binding as a jit operand), so taint flows through them
_PARAM_TRACED_CALLS = {"traced_val", "current_args"}


def _is_jit_call(node: ast.Call) -> bool:
    name = dotted(node.func)
    return name in ("jax.jit", "pjit", "jax.pjit",
                    "jax.experimental.pjit.pjit")


def _is_partial_jit(node: ast.Call) -> bool:
    """functools.partial(jax.jit, ...) used as a decorator."""
    name = dotted(node.func)
    if name not in ("functools.partial", "partial"):
        return False
    return bool(node.args) and dotted(node.args[0]) == "jax.jit"


def _jit_static_names(call: ast.Call, fn: ast.FunctionDef) -> Set[str]:
    """Parameter names excluded from tracing by static_argnums/names."""
    out: Set[str] = set()
    params = [a.arg for a in fn.args.args]
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                s = str_const(v)
                if s:
                    out.add(s)
        elif kw.arg == "static_argnums":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) \
                        and isinstance(v.value, int) \
                        and 0 <= v.value < len(params):
                    out.add(params[v.value])
    return out


def _find_jitted_functions(tree: ast.Module
                           ) -> List[tuple]:
    """[(FunctionDef/Lambda, static_param_names)] for every function the
    module jits: @jax.jit / @functools.partial(jax.jit, ...) decorated
    defs, defs whose name is later passed to jax.jit(...), and lambdas
    appearing directly inside a jax.jit(...) call."""
    defs: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs.setdefault(node.name, node)

    out: List[tuple] = []
    seen: Set[int] = set()

    def add(fn, statics: Set[str]) -> None:
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append((fn, statics))

    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if dotted(dec) == "jax.jit":
                    add(node, set())
                elif isinstance(dec, ast.Call) and (
                        _is_jit_call(dec) or _is_partial_jit(dec)):
                    add(node, _jit_static_names(dec, node))
        elif isinstance(node, ast.Call) and _is_jit_call(node):
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id in defs:
                    fn = defs[arg.id]
                    add(fn, _jit_static_names(node, fn))
                elif isinstance(arg, ast.Lambda):
                    add(arg, set())
    return out


class _TaintWalk:
    """Intraprocedural traced-value taint over one jitted body."""

    def __init__(self, fn, statics: Set[str]):
        self.fn = fn
        args = fn.args
        params = [a.arg for a in args.args + args.kwonlyargs]
        if args.vararg:
            params.append(args.vararg.arg)
        self.tainted: Set[str] = {p for p in params if p not in statics}

    # -- taint queries --------------------------------------------------------
    def _expr_tainted(self, node: ast.expr) -> bool:
        """Does evaluating ``node`` yield a traced VALUE (not just
        structure)? Static reads break the chain."""
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self._expr_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self._expr_tainted(node.value)
        if isinstance(node, (ast.BinOp,)):
            return (self._expr_tainted(node.left)
                    or self._expr_tainted(node.right))
        if isinstance(node, ast.UnaryOp):
            return self._expr_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self._expr_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` is a structure test (pytree
            # arity), static under jit — any other comparison of a
            # traced value is a traced bool
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False
            return (self._expr_tainted(node.left)
                    or any(self._expr_tainted(c)
                           for c in node.comparators))
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name in ("len", "isinstance", "type", "getattr",
                        "hasattr"):
                return False
            if name and name.split(".")[-1] in _PARAM_TRACED_CALLS:
                # params.traced_val/current_args deliver the live
                # binding as a traced operand regardless of arg taint
                return True
            # conservative: a call over traced args returns traced
            return any(self._expr_tainted(a) for a in node.args)
        if isinstance(node, (ast.Tuple, ast.List)):
            # a Python container OF tracers is not itself traced: its
            # truthiness/len is static structure. (Cost: taint doesn't
            # flow through tuple-pack/unpack — acceptable for a gate
            # that must stay green.)
            return False
        if isinstance(node, ast.IfExp):
            return (self._expr_tainted(node.test)
                    or self._expr_tainted(node.body)
                    or self._expr_tainted(node.orelse))
        if isinstance(node, ast.Starred):
            return self._expr_tainted(node.value)
        return False

    def _propagate(self, body: Sequence[ast.stmt]) -> None:
        """One forward pass seeding assigned names (loops in kernels are
        rare; a single pass plus the param seed is enough in practice)."""
        for node in ast.walk(ast.Module(body=list(body),
                                        type_ignores=[])):
            if isinstance(node, ast.Assign) \
                    and self._expr_tainted(node.value):
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            self.tainted.add(n.id)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) \
                    and node.value is not None \
                    and self._expr_tainted(node.value) \
                    and isinstance(node.target, ast.Name):
                self.tainted.add(node.target.id)

    # -- rule application -----------------------------------------------------
    def findings(self, path: str, symbol: str) -> List[Finding]:
        body = (self.fn.body if isinstance(self.fn, ast.FunctionDef)
                else [ast.Expr(value=self.fn.body)])
        self._propagate(body)
        out: List[Finding] = []

        def emit(rule: str, node: ast.AST, msg: str,
                 token: str = "") -> None:
            out.append(Finding(
                CHECKER, rule, path, node.lineno,
                f"{symbol}.{token}" if token else symbol, msg))

        for node in ast.walk(ast.Module(body=list(body),
                                        type_ignores=[])):
            # NOTE: ident tokens carry no line numbers (the baseline
            # contract — see base.py): a suppression covers every
            # same-kind finding on the symbol, which is the right
            # granularity for accepted-by-design code
            if isinstance(node, (ast.If, ast.While)):
                if self._expr_tainted(node.test):
                    kw = "while" if isinstance(node, ast.While) else "if"
                    emit("tracer-branch", node,
                         f"host `{kw}` on a traced value inside jitted "
                         f"function {symbol!r} — use jnp.where/"
                         f"lax.cond, or hoist the decision out of the "
                         f"traced region", kw)
            elif isinstance(node, ast.Call):
                name = dotted(node.func)
                if name in _CONCRETIZING_CASTS and node.args \
                        and self._expr_tainted(node.args[0]):
                    emit("tracer-branch", node,
                         f"{name}() concretizes a traced value inside "
                         f"jitted function {symbol!r}", name)
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" \
                        and self._expr_tainted(node.func.value):
                    emit("tracer-branch", node,
                         f".item() concretizes a traced value inside "
                         f"jitted function {symbol!r}", "item")
                elif name and name.startswith(_NONDET_PREFIXES):
                    emit("nondeterminism", node,
                         f"{name}() inside jitted function {symbol!r} "
                         f"runs once at trace time and freezes into "
                         f"the executable", name)
                elif name and name.split(".")[-1] == "consult":
                    emit("param-bound-read", node,
                         f"params.consult() inside jitted function "
                         f"{symbol!r} — consult is planner-only (it "
                         f"records template reuse guards); kernels "
                         f"must take the binding as a traced operand "
                         f"via traced_val/current_args", "consult")
            elif isinstance(node, ast.Attribute) \
                    and node.attr == "bound" \
                    and isinstance(node.ctx, ast.Load) \
                    and not (isinstance(getattr(node, "parent", None),
                                        ast.Call)
                             and node.parent.func is node):
                # `.bound` VALUE read (a `.bound(...)` method call is
                # the params.bound binding scope, a different thing)
                emit("param-bound-read", node,
                     f".bound read inside jitted function {symbol!r} "
                     f"bakes the BUILD-time binding into the shared "
                     f"executable — every later binding of this "
                     f"template would silently reuse it; read the "
                     f"live value via traced_val/current_args",
                     "bound")
        return out


# -- raw-jit + unbracketed-sync (whole-file rules) ---------------------------

def _inside_timed_entry(node: ast.AST) -> bool:
    for anc in ancestors(node):
        if isinstance(anc, ast.Call):
            name = dotted(anc.func)
            if name and name.split(".")[-1] == "_TimedEntry":
                return True
    return False


def _inside_sync_span(node: ast.AST) -> bool:
    """Lexically under ``with TRACER.span("device-sync"|"jit-compile",
    ...)``, under exec/distributed's ``_sync_record(...)`` (a wrapper
    that opens that exact span AND feeds the mesh flight recorder's
    control_sync bucket — the bracketing contract holds by
    construction), or any ``with`` whose context manager comes from
    the profiler (obs.profiler brackets its own syncs)."""
    for anc in ancestors(node):
        if not isinstance(anc, ast.With):
            continue
        for item in anc.items:
            ctx = item.context_expr
            if not isinstance(ctx, ast.Call):
                continue
            name = dotted(ctx.func) or ""
            if name.split(".")[-1] == "_sync_record":
                return True
            if name.endswith(".span") and ctx.args:
                s = str_const(ctx.args[0])
                if s and (s.startswith("device-sync")
                          or s.startswith("jit-compile")):
                    return True
            if "_prof" in name or "profiler" in name:
                return True
    return False


def _file_findings(path: str, rpath: str,
                   raw_jit_exempt: bool) -> List[Finding]:
    tree = parse_file(path)
    if tree is None:
        return [Finding(CHECKER, "parse-error", rpath, 1, "<module>",
                        "file does not parse")]
    add_parents(tree)
    out: List[Finding] = []

    # rule: raw-jit
    if not raw_jit_exempt:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and (
                    _is_jit_call(node) or _is_partial_jit(node)):
                if _inside_timed_entry(node):
                    continue
                sym = enclosing_symbol(node)
                out.append(Finding(
                    CHECKER, "raw-jit", rpath, node.lineno, sym,
                    f"direct {dotted(node.func)} call bypasses "
                    f"ops/jitcache — wrap in _TimedEntry (or an "
                    f"_entry_cache) so compiles/invocations/device "
                    f"time are profiled and recompiles are capped"))
            elif isinstance(node, ast.Attribute) \
                    and dotted(node) == "jax.jit" \
                    and isinstance(getattr(node, "parent", None),
                                   (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                # bare @jax.jit decorator (non-call form)
                sym = node.parent.name  # type: ignore[attr-defined]
                out.append(Finding(
                    CHECKER, "raw-jit", rpath, node.lineno, sym,
                    "bare @jax.jit decorator bypasses ops/jitcache — "
                    "wrap in _TimedEntry"))

    # rule: unbracketed-sync
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or ""
        is_sync = (name in ("jax.device_get",)
                   or name.endswith(".block_until_ready"))
        if is_sync and not _inside_sync_span(node):
            sym = enclosing_symbol(node)
            what = ("jax.device_get" if name == "jax.device_get"
                    else "block_until_ready")
            out.append(Finding(
                CHECKER, "unbracketed-sync", rpath, node.lineno,
                f"{sym}.{what}",
                f"{what} outside a TRACER.span(\"device-sync\") "
                f"scope — deliberate device round-trips must be "
                f"observable stalls"))

    # rules: tracer-branch / nondeterminism (per jitted function)
    for fn, statics in _find_jitted_functions(tree):
        symbol = (fn.name if isinstance(fn, ast.FunctionDef)
                  else f"<lambda>:{fn.lineno}")
        out.extend(_TaintWalk(fn, statics).findings(rpath, symbol))
    return out


def check_paths(paths: Sequence[str], root: str,
                raw_jit_allowed: Sequence[str] = RAW_JIT_ALLOWED_FILES
                ) -> List[Finding]:
    out: List[Finding] = []
    for p in paths:
        rpath = rel(p, root)
        out.extend(_file_findings(p, rpath,
                                  raw_jit_exempt=rpath in raw_jit_allowed))
    return out


def check(root: str, scope: Sequence[str] = SCOPE) -> List[Finding]:
    return check_paths(sorted(set(walk_py(root, scope))), root)

"""CLI for the static-analysis plane: ``python -m tools.analyze``.

Modes:

- default: full scan, exit 0 iff no unsuppressed findings and no stale
  baseline suppressions;
- ``--changed``: git-diff-scoped fast mode (per-file rules on the
  working-tree delta only; registry rules one-way unless a declaring
  input changed; stale detection skipped) — the pre-commit loop;
- ``--format json``: machine-readable verdict on stdout for CI
  tooling, same shape as tools/check_bench_regression.py's output
  discipline (one JSON document, ``ok`` is the gate).
"""
from __future__ import annotations

import argparse
import json
import sys

from . import CHECKERS, git_changed_files, run, run_changed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="repo static analysis: trace safety, lock "
                    "discipline, registry consistency, cache-protocol "
                    "contracts")
    ap.add_argument("--checker", action="append", choices=sorted(CHECKERS),
                    help="run only this checker (repeatable; full-scan "
                         "mode only)")
    ap.add_argument("--root", default=None,
                    help="repo root to scan (default: this repo)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore baseline.json suppressions")
    ap.add_argument("--list", action="store_true",
                    help="also print baseline-suppressed findings")
    ap.add_argument("--changed", action="store_true",
                    help="fast mode: scan only the git working-tree "
                         "delta (skips stale-suppression detection)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="output format (default text)")
    args = ap.parse_args(argv)

    baseline = "/dev/null" if args.no_baseline else None
    if args.changed:
        files = git_changed_files(args.root)
        findings, suppressed, stale = run_changed(
            files, root=args.root, baseline_path=baseline)
    else:
        findings, suppressed, stale = run(
            root=args.root, checkers=args.checker,
            baseline_path=baseline)

    ok = not findings and not stale
    if args.format == "json":
        doc = {
            "ok": ok,
            "mode": "changed" if args.changed else "full",
            "findings": [
                {"checker": f.checker, "rule": f.rule, "path": f.path,
                 "line": f.line, "symbol": f.symbol, "ident": f.ident,
                 "message": f.message}
                for f in findings],
            "suppressed": len(suppressed),
            "stale_suppressions": stale,
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0 if ok else 1

    for f in findings:
        print(f.render(), file=sys.stderr)
    if args.list:
        for f in suppressed:
            print(f"[baseline] {f.render()}")
    for ident in stale:
        print(f"stale baseline suppression (fixed? delete it): "
              f"{ident}", file=sys.stderr)
    print(f"{'ok' if ok else 'FAIL'}: {len(findings)} finding(s), "
          f"{len(suppressed)} baseline-suppressed, "
          f"{len(stale)} stale suppression(s)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""CLI for the static-analysis plane: ``python -m tools.analyze``."""
from __future__ import annotations

import argparse
import sys

from . import CHECKERS, run


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="repo static analysis: trace safety, lock "
                    "discipline, registry consistency")
    ap.add_argument("--checker", action="append", choices=sorted(CHECKERS),
                    help="run only this checker (repeatable)")
    ap.add_argument("--root", default=None,
                    help="repo root to scan (default: this repo)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore baseline.json suppressions")
    ap.add_argument("--list", action="store_true",
                    help="also print baseline-suppressed findings")
    args = ap.parse_args(argv)

    findings, suppressed, stale = run(
        root=args.root, checkers=args.checker,
        baseline_path="/dev/null" if args.no_baseline else None)

    for f in findings:
        print(f.render(), file=sys.stderr)
    if args.list:
        for f in suppressed:
            print(f"[baseline] {f.render()}")
    for ident in stale:
        print(f"stale baseline suppression (fixed? delete it): "
              f"{ident}", file=sys.stderr)

    ok = not findings and not stale
    print(f"{'ok' if ok else 'FAIL'}: {len(findings)} finding(s), "
          f"{len(suppressed)} baseline-suppressed, "
          f"{len(stale)} stale suppression(s)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Shared plumbing for the repo's static-analysis plane.

The reference engine keeps its codegen layer honest with a wall of
targeted Error-Prone / checkstyle rules compiled into the build
(presto-main's sql/gen/ discipline); this package is our equivalent,
sized to the three failure classes that actually bite a JAX/XLA query
engine: host control flow on tracers, thread-pool lock discipline, and
string-keyed registries where a typo is a silent no-op.

Contracts every checker follows:

- ``check(root)`` walks its declared scope under the repo root and
  returns :class:`Finding`\\ s. Checkers are pure AST walkers — they
  never import the engine, so they run in milliseconds and can't be
  confused by environment (no jax, no device).
- A finding's :attr:`Finding.ident` is stable across unrelated edits:
  ``checker:rule:path:symbol`` (no line numbers), where ``symbol`` is
  the enclosing function/class qualname or the offending token. The
  committed ``baseline.json`` suppresses by ident, so an accepted
  pre-existing finding doesn't block CI while any NEW instance of the
  same rule elsewhere still fails.
- Stale baseline entries (nothing matches anymore) are themselves
  errors: when a finding is fixed, its suppression must be deleted in
  the same change, keeping the accepted-debt list honest.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str      # tracing | locks | registries
    rule: str         # e.g. raw-jit, lock-cycle, unknown-session-prop
    path: str         # repo-relative, forward slashes
    line: int
    symbol: str       # enclosing qualname / offending token (ident key)
    message: str

    @property
    def ident(self) -> str:
        return f"{self.checker}:{self.rule}:{self.path}:{self.symbol}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.checker}/{self.rule}] "
                f"{self.message}")


def rel(path: str, root: Optional[str] = None) -> str:
    return os.path.relpath(path, root or REPO).replace(os.sep, "/")


def parse_file(path: str) -> Optional[ast.Module]:
    with open(path, encoding="utf-8", errors="replace") as f:
        src = f.read()
    try:
        return ast.parse(src, filename=path)
    except SyntaxError:
        return None


def walk_py(root: str, subpaths: Iterable[str]) -> Iterator[str]:
    """Yield .py files under ``root`` for each subpath (a directory is
    walked recursively, a file yielded as-is; missing entries skipped so
    checkers degrade gracefully on fixture trees)."""
    for sub in subpaths:
        p = os.path.join(root, sub)
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def add_parents(tree: ast.AST) -> None:
    """Annotate every node with ``.parent`` (ast has no uplinks)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, "parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "parent", None)


def enclosing_symbol(node: ast.AST) -> str:
    """Dotted qualname of the enclosing defs/classes, '<module>' at
    top level — the stable half of a finding ident."""
    names: List[str] = []
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.append(anc.name)
    return ".".join(reversed(names)) or "<module>"


def dotted(node: ast.expr) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def str_const(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# -- baseline ----------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, str]:
    """ident -> reason. Missing file = empty baseline."""
    if not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as f:
        text = f.read().strip()
    doc = json.loads(text) if text else {}
    out: Dict[str, str] = {}
    for entry in doc.get("suppressions", ()):
        out[entry["id"]] = entry.get("reason", "")
    return out


def apply_baseline(findings: List[Finding], baseline: Dict[str, str]
                   ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """-> (unsuppressed, suppressed, stale baseline idents)."""
    hit: set = set()
    keep: List[Finding] = []
    dropped: List[Finding] = []
    for f in findings:
        if f.ident in baseline:
            hit.add(f.ident)
            dropped.append(f)
        else:
            keep.append(f)
    stale = sorted(set(baseline) - hit)
    return keep, dropped, stale

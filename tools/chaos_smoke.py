#!/usr/bin/env python
"""Chaos smoke: a 3-worker in-process cluster under seeded failpoints.

Drives every recovery path of the fault-tolerance layer
(presto_tpu/exec/cluster.py + exec/failpoints.py) without a real
multi-host TPU cluster, and asserts ROW-EXACT parity with the
fault-free run after each injected fault:

- ``task_failure``   — one task FAILs at start (``worker.task_run``
  error); the coordinator re-creates it on a healthy worker.
- ``exchange_drop``  — one exchange pull dies mid-stream
  (``exchange.pull`` error); the ExchangeFailedError names the upstream
  attempt and the retry layer replaces exactly that producer.
- ``straggler``      — one source task sleeps 15s (``worker.task_run``
  sleep); the StageMonitor flags it, a speculative duplicate launches
  on another node and wins, the loser is aborted.
- ``retry_none``     — same task fault under ``retry_policy=NONE``
  fails fast (the pre-fault-tolerance behavior, still available).
- ``worker_death``   — a failpoint callback kills one worker's HTTP
  server mid-query; its tasks (same deterministic splits) reschedule
  onto the survivors.

Recovery is asserted observable: ``task_retry_total`` and
``speculative_won_total`` move, via ``system.runtime.metrics`` over
plain SQL.

Run directly (prints a JSON summary) or from the tier-1 suite
(tests/test_chaos.py):

    JAX_PLATFORMS=cpu python tools/chaos_smoke.py [--sf 0.01]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

QUERY = ("select l_returnflag, l_linestatus, count(*) c, "
         "sum(l_quantity) q, sum(l_extendedprice) e from lineitem "
         "where l_shipdate <= date '1998-09-02' "
         "group by 1, 2 order by 1, 2")


def _metric_sql(runner, name: str) -> float:
    res = runner.local.execute(
        "select value from system.runtime.metrics "
        f"where name = '{name}'")
    return float(res.rows[0][0]) if res.rows else 0.0


def _assert_rows_equal(got, want, scenario: str) -> None:
    assert len(got) == len(want), \
        f"{scenario}: {len(got)} rows vs {len(want)}"
    for gr, wr in zip(got, want):
        for gv, wv in zip(gr, wr):
            if isinstance(wv, float):
                # partial-agg pages merge in arrival order; float sums
                # are reproducible only to rounding, like test_cluster
                assert abs(gv - wv) <= max(abs(wv), 1.0) * 1e-6, \
                    (scenario, gr, wr)
            else:
                assert gv == wv, (scenario, gr, wr)


def run_chaos(sf: float = 0.01, query: str = QUERY,
              verbose: bool = False) -> dict:
    from presto_tpu.exec.cluster import ClusterRunner, QueryFailedError
    from presto_tpu.exec.failpoints import FAILPOINTS
    from presto_tpu.server.worker import WorkerServer

    def log(msg: str) -> None:
        if verbose:
            print(msg, file=sys.stderr, flush=True)

    workers = [WorkerServer(tpch_sf=sf) for _ in range(3)]
    for w in workers:
        w.start()
    urls = [f"http://127.0.0.1:{w.port}" for w in workers]
    runner = ClusterRunner(urls, tpch_sf=sf, heartbeat=False)
    summary: dict = {"sf": sf, "scenarios": {}}
    FAILPOINTS.clear()
    try:
        # fault-free reference (first run also warms the jit caches so
        # fault-run timings measure recovery, not compilation)
        t0 = time.perf_counter()
        want = runner.execute(query).rows
        runner.execute(query)
        summary["baseline_s"] = round(time.perf_counter() - t0, 3)
        log(f"baseline: {len(want)} rows in {summary['baseline_s']}s")

        def scenario(name: str):
            t = time.perf_counter()

            def finish(**extra):
                FAILPOINTS.clear()
                summary["scenarios"][name] = {
                    "elapsed_s": round(time.perf_counter() - t, 3),
                    **extra}
                log(f"{name}: ok {summary['scenarios'][name]}")
            return finish

        # -- (a) one task failure -> task-level retry ---------------------
        finish = scenario("task_failure")
        before = _metric_sql(runner, "task_retry_total")
        FAILPOINTS.configure("worker.task_run", action="error",
                             message="chaos: task failure", times=1)
        _assert_rows_equal(runner.execute(query).rows, want,
                           "task_failure")
        retries = _metric_sql(runner, "task_retry_total") - before
        assert retries >= 1, "task failure did not trigger a retry"
        finish(task_retries=retries)

        # -- (b) exchange drop mid-stream -> upstream replaced ------------
        finish = scenario("exchange_drop")
        before = _metric_sql(runner, "task_retry_total")
        FAILPOINTS.configure("exchange.pull", action="error",
                             message="chaos: exchange drop", times=1)
        _assert_rows_equal(runner.execute(query).rows, want,
                           "exchange_drop")
        retries = _metric_sql(runner, "task_retry_total") - before
        assert retries >= 1, "exchange drop did not trigger a retry"
        finish(task_retries=retries)

        # -- (c) 10x straggler -> speculative attempt wins ----------------
        finish = scenario("straggler")
        before = _metric_sql(runner, "speculative_won_total")
        # partition 0 of the source stage sleeps far past the stage
        # median; attempt suffixes keep the duplicate out of the rule
        FAILPOINTS.configure("worker.task_run", action="sleep",
                             sleep_s=15.0, match=r"\.0\.0@", times=1)
        _assert_rows_equal(runner.execute(query).rows, want,
                           "straggler")
        won = _metric_sql(runner, "speculative_won_total") - before
        assert won >= 1, "straggler did not produce a speculative win"
        finish(speculative_won=won)

        # -- (d) retry_policy=NONE fails fast -----------------------------
        finish = scenario("retry_none")
        FAILPOINTS.configure("worker.task_run", action="error",
                             message="chaos: fail fast", times=1)
        runner.session.properties["retry_policy"] = "NONE"
        try:
            failed = False
            try:
                runner.execute(query)
            except QueryFailedError as e:
                failed = True
                assert "chaos: fail fast" in str(e), str(e)
            assert failed, "retry_policy=NONE still recovered"
        finally:
            del runner.session.properties["retry_policy"]
        finish()

        # -- (e) worker death mid-query -> reschedule on survivors --------
        # (last: the victim stays dead for the rest of the run)
        finish = scenario("worker_death")
        before = _metric_sql(runner, "task_retry_total")
        victim = workers[-1]

        def kill(key="", **ctx):
            victim.httpd.shutdown()
            victim.httpd.server_close()
            # a real worker death takes its task threads with it; the
            # in-process stand-in kills the network surface above and
            # the compute below, so zombies don't hold the shared
            # device scheduler
            for t in list(victim.tasks.values()):
                t.abort()

        FAILPOINTS.configure("worker.task_run", action="callback",
                             callback=kill, times=1,
                             match=f"@{victim.node_id}$")
        _assert_rows_equal(runner.execute(query).rows, want,
                           "worker_death")
        retries = _metric_sql(runner, "task_retry_total") - before
        assert retries >= 1, "worker death did not trigger a retry"
        # the dead node must be out of the schedulable set now
        assert f"http://127.0.0.1:{victim.port}" \
            not in runner._schedulable_workers()
        finish(task_retries=retries)

        # the retry count is part of the query history record
        res = runner.local.execute(
            "select retries from system.runtime.completed_queries "
            "where mode = 'cluster' order by create_time")
        assert res.rows and any(int(r[0]) >= 1 for r in res.rows), \
            "no completed_queries record carries a retry count"

        # -- (f) typo'd spec rejected at parse time -----------------------
        # a chaos config naming an unregistered site would inject
        # nothing and "pass" every scenario above — the registry must
        # refuse to arm it (exec/failpoints.py SITES validation)
        finish = scenario("failpoint_validation")
        rejected = False
        try:
            FAILPOINTS.configure_from_spec("worker.task_ruin=error")
        except ValueError as e:
            rejected = "unknown failpoint site" in str(e)
        assert rejected, "typo'd failpoint spec was silently accepted"
        finish(rejected=True)
        summary["ok"] = True
        return summary
    finally:
        FAILPOINTS.clear()
        for w in workers:
            try:
                w.stop()
            except Exception:
                pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sf", type=float, default=0.01,
                    help="TPC-H scale factor (default 0.01)")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)
    summary = run_chaos(sf=args.sf, verbose=not args.quiet)
    print(json.dumps(summary, indent=2))
    return 0 if summary.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())

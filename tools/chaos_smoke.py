#!/usr/bin/env python
"""Chaos smoke: an elastic in-process cluster under seeded failpoints.

Drives every recovery path of the fault-tolerance + spooled-exchange
layers (presto_tpu/exec/cluster.py, exec/spool.py, exec/failpoints.py)
without a real multi-host TPU cluster, and asserts ROW-EXACT parity
with the fault-free run after each injected fault:

- ``task_failure``   — one task FAILs at start (``worker.task_run``
  error); the coordinator re-creates it on a healthy worker.
- ``exchange_drop``  — one exchange pull dies mid-stream
  (``exchange.pull`` error); the ExchangeFailedError names the upstream
  attempt and the retry layer replaces exactly that producer.
- ``straggler``      — one source task sleeps 15s (``worker.task_run``
  sleep); the StageMonitor flags it, a speculative duplicate launches
  on another node and wins, the loser is aborted.
- ``retry_none``     — same task fault under ``retry_policy=NONE``
  fails fast (the pre-fault-tolerance behavior, still available).
- ``worker_death``   — a failpoint callback kills one worker's HTTP
  server mid-query; its tasks (same deterministic splits) reschedule
  onto the survivors.
- ``spool_replay``   — a worker is killed AFTER its source task
  committed its spool, mid-shuffle: consumers replay the pages from
  the durable spool and the source task is NOT re-executed (asserted
  via the task-attempt/retry events — the spooled-exchange headline).
- ``spool_corrupt``  — one spooled page is corrupted on disk
  (``spool.corrupt``) and its worker killed: the checksum catches it,
  the consumer's failure names the upstream, and the retry layer
  re-runs exactly that producer; results stay row-exact.
- ``worker_join``    — a FRESH worker boots and announces mid-query
  while another dies: the re-created tasks land on the late joiner
  (elastic scale-out under the discovery + recovery machinery).
- ``drain_exit``     — a worker is put into SHUTTING_DOWN mid-query
  while the root is still reading its output: it exits within its
  drain grace (no lingering until downstream completion) and the
  consumer finishes from the spool, with zero task retries.

Recovery is asserted observable: ``task_retry_total``,
``speculative_won_total``, ``spool_replayed_task_total``,
``exchange_spool_fallback_total`` and ``node_joined_total`` move, via
``system.runtime.metrics`` over plain SQL; at the end the spool
directory must hold ZERO orphaned per-query directories.

Run directly (prints a JSON summary) or from the tier-1 suite
(tests/test_chaos.py):

    JAX_PLATFORMS=cpu python tools/chaos_smoke.py [--sf 0.01]

``--elastic-out PATH`` (or the ``ELASTIC_OUT`` env var) additionally
writes a bench-style summary of per-scenario recovery times, gated by
``tools/check_bench_regression.py --kind elastic`` against the
committed ``ELASTIC_r*.json``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

QUERY = ("select l_returnflag, l_linestatus, count(*) c, "
         "sum(l_quantity) q, sum(l_extendedprice) e from lineitem "
         "where l_shipdate <= date '1998-09-02' "
         "group by 1, 2 order by 1, 2")

# the load-ramp bench's query: a selective SCAN, not an aggregate. Its
# device cost is the batches scanned (input-proportional — that's what
# shrinks per worker as the pool grows), while its tiny result keeps
# exchange/sort/client cost flat. An aggregate collapses each task to
# ~one output page, so modeled per-worker cost would never scale.
RAMP_QUERY = ("select l_orderkey, l_linenumber, l_extendedprice "
              "from lineitem where l_extendedprice > 90000 "
              "order by 1, 2")


def _metric_sql(runner, name: str) -> float:
    res = runner.local.execute(
        "select value from system.runtime.metrics "
        f"where name = '{name}'")
    return float(res.rows[0][0]) if res.rows else 0.0


def _assert_rows_equal(got, want, scenario: str) -> None:
    assert len(got) == len(want), \
        f"{scenario}: {len(got)} rows vs {len(want)}"
    for gr, wr in zip(got, want):
        for gv, wv in zip(gr, wr):
            if isinstance(wv, float):
                # partial-agg pages merge in arrival order; float sums
                # are reproducible only to rounding, like test_cluster
                assert abs(gv - wv) <= max(abs(wv), 1.0) * 1e-6, \
                    (scenario, gr, wr)
            else:
                assert gv == wv, (scenario, gr, wr)


def run_chaos(sf: float = 0.01, query: str = QUERY,
              verbose: bool = False) -> dict:
    from presto_tpu.exec.cluster import ClusterRunner, QueryFailedError
    from presto_tpu.exec.discovery import DiscoveryNodeManager
    from presto_tpu.exec.failpoints import FAILPOINTS
    from presto_tpu.exec.spool import SPOOL
    from presto_tpu.server.worker import WorkerServer

    def log(msg: str) -> None:
        if verbose:
            print(msg, file=sys.stderr, flush=True)

    # discovery-fed membership (not a static URL list): workers may
    # join or leave mid-query — the elastic half of the smoke
    discovery = DiscoveryNodeManager(ttl_s=3600.0)
    workers = []

    def add_worker() -> WorkerServer:
        w = WorkerServer(tpch_sf=sf, drain_grace_s=2.0)
        w.start()
        workers.append(w)
        discovery.announce(w.node_id, f"http://127.0.0.1:{w.port}")
        return w

    def kill_worker(w: WorkerServer) -> None:
        """In-process stand-in for a worker process death: the network
        surface goes away AND its task threads stop burning the shared
        device scheduler."""
        w.httpd.shutdown()
        w.httpd.server_close()
        for t in list(w.tasks.values()):
            t.abort()

    for _ in range(3):
        add_worker()
    runner = ClusterRunner(tpch_sf=sf, heartbeat=False,
                           discovery=discovery)
    summary: dict = {"sf": sf, "scenarios": {}}
    FAILPOINTS.clear()
    try:
        # fault-free reference (first run also warms the jit caches so
        # fault-run timings measure recovery, not compilation)
        t0 = time.perf_counter()
        want = runner.execute(query).rows
        runner.execute(query)
        summary["baseline_s"] = round(time.perf_counter() - t0, 3)
        log(f"baseline: {len(want)} rows in {summary['baseline_s']}s")

        def scenario(name: str):
            t = time.perf_counter()

            def finish(**extra):
                FAILPOINTS.clear()
                summary["scenarios"][name] = {
                    "elapsed_s": round(time.perf_counter() - t, 3),
                    **extra}
                log(f"{name}: ok {summary['scenarios'][name]}")
            return finish

        # -- (a) one task failure -> task-level retry ---------------------
        finish = scenario("task_failure")
        before = _metric_sql(runner, "task_retry_total")
        FAILPOINTS.configure("worker.task_run", action="error",
                             message="chaos: task failure", times=1)
        _assert_rows_equal(runner.execute(query).rows, want,
                           "task_failure")
        retries = _metric_sql(runner, "task_retry_total") - before
        assert retries >= 1, "task failure did not trigger a retry"
        finish(task_retries=retries)

        # -- (b) exchange drop mid-stream -> upstream replaced ------------
        finish = scenario("exchange_drop")
        before = _metric_sql(runner, "task_retry_total")
        FAILPOINTS.configure("exchange.pull", action="error",
                             message="chaos: exchange drop", times=1)
        _assert_rows_equal(runner.execute(query).rows, want,
                           "exchange_drop")
        retries = _metric_sql(runner, "task_retry_total") - before
        assert retries >= 1, "exchange drop did not trigger a retry"
        finish(task_retries=retries)

        # -- (c) 10x straggler -> speculative attempt wins ----------------
        finish = scenario("straggler")
        before = _metric_sql(runner, "speculative_won_total")
        # partition 0 of the source stage sleeps far past the stage
        # median; attempt suffixes keep the duplicate out of the rule.
        # The sleep must also outlast a COLD duplicate: on a loaded
        # 1-core host the speculative attempt may land on a worker
        # that never compiled this fragment (~9s JIT) — 15s let the
        # original occasionally wake first and steal the win
        FAILPOINTS.configure("worker.task_run", action="sleep",
                             sleep_s=30.0, match=r"\.0\.0@", times=1)
        # ... and the SIBLING source tasks must clear the monitor's
        # straggler median floor (min_elapsed_ms): with the scan cache
        # primed by the earlier scenarios they finish in a few ms, the
        # stage median lands under the floor, and the straggler is
        # never flagged — the exact warm-cluster shape that made this
        # scenario order-dependent inside the full test suite
        FAILPOINTS.configure("worker.task_run", action="sleep",
                             sleep_s=0.1, match=r"\.0\.[1-9]\d*@",
                             times=None)
        _assert_rows_equal(runner.execute(query).rows, want,
                           "straggler")
        FAILPOINTS.clear()      # the sibling pad rule is unbounded
        won = _metric_sql(runner, "speculative_won_total") - before
        assert won >= 1, "straggler did not produce a speculative win"
        finish(speculative_won=won)

        # -- (d) retry_policy=NONE fails fast -----------------------------
        finish = scenario("retry_none")
        FAILPOINTS.configure("worker.task_run", action="error",
                             message="chaos: fail fast", times=1)
        runner.session.properties["retry_policy"] = "NONE"
        try:
            failed = False
            try:
                runner.execute(query)
            except QueryFailedError as e:
                failed = True
                assert "chaos: fail fast" in str(e), str(e)
            assert failed, "retry_policy=NONE still recovered"
        finally:
            del runner.session.properties["retry_policy"]
        finish()

        # -- (e) worker death mid-query -> reschedule on survivors --------
        finish = scenario("worker_death")
        before = _metric_sql(runner, "task_retry_total")
        victim = workers[-1]

        def kill(key="", **ctx):
            kill_worker(victim)

        FAILPOINTS.configure("worker.task_run", action="callback",
                             callback=kill, times=1,
                             match=f"@{victim.node_id}$")
        _assert_rows_equal(runner.execute(query).rows, want,
                           "worker_death")
        retries = _metric_sql(runner, "task_retry_total") - before
        assert retries >= 1, "worker death did not trigger a retry"
        # the dead node must be out of the schedulable set now
        assert f"http://127.0.0.1:{victim.port}" \
            not in runner._schedulable_workers()
        finish(task_retries=retries)
        add_worker()               # replenish the pool to 3 live nodes

        # fragment ids of the smoke query (the scenarios below target
        # the source stage's tasks / the stage the root consumes)
        from presto_tpu.planner.fragmenter import fragment_plan
        from presto_tpu.planner.plan import RemoteSourceNode
        fp = fragment_plan(runner.local.plan(query).root)
        source_fid = next(f.id for f in fp.fragments
                          if f.partitioning == "source")

        def _nodes(n):
            yield n
            for c in n.children:
                yield from _nodes(c)
        feed_fid = next(fid for node in _nodes(fp.root.root)
                        if isinstance(node, RemoteSourceNode)
                        for fid in node.fragment_ids)

        def live_workers():
            return [w for w in workers if w.httpd.socket.fileno() != -1
                    and not w.shutting_down]

        def pick_victim():
            # the single (root) fragment lands on the first worker of
            # the schedulable sweep (sorted by URL): the max-URL live
            # worker can never host the root, which keeps the
            # drain/kill scenarios' retry accounting deterministic
            return max(live_workers(),
                       key=lambda w: f"http://127.0.0.1:{w.port}")

        def wait_stage_finished(w: WorkerServer, fid: int,
                                timeout_s: float = 30.0) -> None:
            deadline = time.time() + timeout_s
            while time.time() < deadline:
                ts = [t for t in list(w.tasks.values())
                      if t.task_id.split(".")[1] == str(fid)]
                if ts and all(t.state == "FINISHED" for t in ts):
                    return
                time.sleep(0.05)
            raise AssertionError(
                f"stage {fid} on {w.node_id} never finished")

        # -- (f) spool replay: kill a worker AFTER its source task ------
        # committed its spool, mid-shuffle. Consumers replay the pages
        # from the durable spool; the source task is NOT re-executed.
        finish = scenario("spool_replay")
        before = _metric_sql(runner, "task_retry_total")
        before_replay = _metric_sql(runner, "spool_replayed_task_total")
        before_fb = _metric_sql(runner,
                                "exchange_spool_fallback_total")
        victim2 = pick_victim()
        killed = threading.Event()
        kill_lock = threading.Lock()

        def kill_after_spool(key="", **ctx):
            # EVERY pull of the victim's source task funnels through
            # here (times unlimited): no page is ever served live, so
            # every consumer must replay from the spool — and the kill
            # only lands once the spool is committed
            with kill_lock:
                if not killed.is_set():
                    wait_stage_finished(victim2, source_fid)
                    kill_worker(victim2)
                    killed.set()

        FAILPOINTS.configure(
            "exchange.pull", action="callback",
            callback=kill_after_spool, times=None,
            match=rf":{victim2.port}/v1/task/[^/]*\.{source_fid}\.\d+$")
        _assert_rows_equal(runner.execute(query).rows, want,
                           "spool_replay")
        FAILPOINTS.clear()
        replays = _metric_sql(
            runner, "spool_replayed_task_total") - before_replay
        fallbacks = _metric_sql(
            runner, "exchange_spool_fallback_total") - before_fb
        retries = _metric_sql(runner, "task_retry_total") - before
        assert replays >= 1, \
            "lost-but-spooled task was not preserved"
        assert fallbacks >= 1, \
            "no consumer replayed from the spool"
        # the headline assertion: NO source-stage task was re-executed
        # (retries are the victim's other tasks — never the producer
        # whose output lives in the spool)
        events = runner._last_run_info.get("events") or []
        source_retries = [
            ev for ev in events if ev.get("kind") == "task_retry"
            and str(ev.get("task", "")).split(".")[1]
            == str(source_fid)]
        assert not source_retries, \
            f"spooled source task was re-executed: {source_retries}"
        finish(spool_replays=replays, spool_fallbacks=fallbacks,
               task_retries=retries)
        add_worker()

        # -- (g) spool corruption: checksum -> retry from upstream ------
        finish = scenario("spool_corrupt")
        before = _metric_sql(runner, "task_retry_total")
        before_cor = _metric_sql(runner, "spool_corruption_total")
        victim3 = pick_victim()
        killed3 = threading.Event()
        kill3_lock = threading.Lock()
        corrupt_armed = threading.Event()

        def arm_corrupt(key="", task_id="", **ctx):
            # corrupt the first spooled page of a source task ON THE
            # VICTIM (the task id is only known once the worker starts
            # it): the frame keeps the original checksum, the payload
            # flips one byte on disk. Arming by exact task id matters:
            # a survivor's corrupted page would be served from the
            # clean in-memory fast path and never detected.
            import re as _re
            if task_id.split(".")[1] == str(source_fid) \
                    and not corrupt_armed.is_set():
                corrupt_armed.set()
                FAILPOINTS.configure(
                    "spool.corrupt", action="error", times=1,
                    match=rf"^{_re.escape(task_id)}/")

        FAILPOINTS.configure("worker.task_run", action="callback",
                             callback=arm_corrupt, times=None,
                             match=f"@{victim3.node_id}$")

        def kill_after_corrupt(key="", **ctx):
            with kill3_lock:
                if not killed3.is_set():
                    wait_stage_finished(victim3, source_fid)
                    kill_worker(victim3)
                    killed3.set()

        FAILPOINTS.configure(
            "exchange.pull", action="callback",
            callback=kill_after_corrupt, times=None,
            match=rf":{victim3.port}/v1/task/[^/]*\.{source_fid}\.\d+$")
        _assert_rows_equal(runner.execute(query).rows, want,
                           "spool_corrupt")
        FAILPOINTS.clear()
        corruptions = _metric_sql(
            runner, "spool_corruption_total") - before_cor
        retries = _metric_sql(runner, "task_retry_total") - before
        assert corrupt_armed.is_set(), \
            "victim never ran a source task to corrupt"
        assert corruptions >= 1, \
            "corrupted spool page was served without detection"
        assert retries >= 1, \
            "spool corruption did not re-run the producer"
        finish(corruptions=corruptions, task_retries=retries)
        add_worker()

        # -- (h) elastic join: a fresh worker boots + announces -------
        # mid-query while another dies; the re-created tasks land on
        # the late joiner
        finish = scenario("worker_join")
        before = _metric_sql(runner, "task_retry_total")
        before_join = _metric_sql(runner, "node_joined_total")
        victim4 = pick_victim()
        joiner: dict = {}

        def kill_and_join(key="", **ctx):
            kill_worker(victim4)
            joiner["w"] = add_worker()

        FAILPOINTS.configure("worker.task_run", action="callback",
                             callback=kill_and_join, times=1,
                             match=f"@{victim4.node_id}$")
        _assert_rows_equal(runner.execute(query).rows, want,
                           "worker_join")
        FAILPOINTS.clear()
        retries = _metric_sql(runner, "task_retry_total") - before
        joined = _metric_sql(runner, "node_joined_total") - before_join
        assert retries >= 1, "worker death did not trigger a retry"
        assert joined >= 1, "the late joiner was never federated"
        joiner_url = f"http://127.0.0.1:{joiner['w'].port}"
        events = runner._last_run_info.get("events") or []
        landed = [ev for ev in events
                  if ev.get("kind") == "task_retry"
                  and ev.get("to") == joiner_url]
        assert landed, \
            f"no re-created task landed on the late joiner: {events}"
        finish(task_retries=retries, joined=joined,
               landed_on_joiner=len(landed))

        # -- (i) drain-and-exit: SHUTTING_DOWN mid-read ----------------
        # the worker exits within its drain grace while the root is
        # still consuming its output; the root finishes from the spool
        # with ZERO task retries
        finish = scenario("drain_exit")
        before = _metric_sql(runner, "task_retry_total")
        before_fb = _metric_sql(runner,
                                "exchange_spool_fallback_total")
        victim5 = pick_victim()
        drained = threading.Event()
        drain_lock = threading.Lock()

        def drain_after_finish(key="", **ctx):
            with drain_lock:
                if not drained.is_set():
                    wait_stage_finished(victim5, feed_fid)
                    victim5.begin_shutdown()
                    drained.set()

        # the root's pulls of the victim's feed-stage task trigger the
        # drain (once that task finished), then slow to one page per
        # second — guaranteeing the worker is GONE before the root
        # drains the buffer, so the tail must come from the spool
        FAILPOINTS.configure(
            "exchange.pull", action="callback",
            callback=drain_after_finish, times=None,
            match=rf":{victim5.port}/v1/task/[^/]*\.{feed_fid}\.\d+$")
        FAILPOINTS.configure(
            "exchange.pull", action="sleep", sleep_s=1.0, times=None,
            match=rf":{victim5.port}/v1/task/[^/]*\.{feed_fid}\.\d+$")
        _assert_rows_equal(runner.execute(query).rows, want,
                           "drain_exit")
        FAILPOINTS.clear()
        retries = _metric_sql(runner, "task_retry_total") - before
        fallbacks = _metric_sql(
            runner, "exchange_spool_fallback_total") - before_fb
        assert retries == 0, \
            f"drain caused {retries} retries (spool should replay)"
        assert fallbacks >= 1, \
            "root never replayed the drained worker's output"
        # the drained worker's process actually EXITED within its
        # grace (no lingering until downstream completion): its socket
        # must refuse within a short post-query window
        exit_deadline = time.time() + 5.0
        gone = False
        while time.time() < exit_deadline:
            try:
                import urllib.request
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{victim5.port}/v1/info",
                        timeout=1):
                    pass
            except Exception:
                gone = True
                break
            time.sleep(0.1)
        assert gone, "drained worker lingered past its grace"
        assert f"http://127.0.0.1:{victim5.port}" \
            not in runner._schedulable_workers()
        finish(task_retries=retries, spool_fallbacks=fallbacks)

        # -- (j) preemption storm: workers are preemptible BY DESIGN ---
        # Poisson-cadence preemptions (seeded — replayable) under
        # sustained query load: every preemption is a drain notice
        # (begin_shutdown → active tasks commit their spool → process
        # exit), a replacement joins, and ZERO queries fail. The first
        # preemption is deterministic (the drain_exit recipe) so at
        # least one coordinator-side spool replay is guaranteed
        # regardless of storm timing.
        import random as _random
        finish = scenario("preemption_storm")
        # drain_exit left the pool at two live workers; the storm
        # needs three so its >=2-live preemption guard has headroom
        # after the deterministic first drain
        while len(live_workers()) < 3:
            add_worker()
        before_replay = _metric_sql(runner, "spool_replayed_task_total")
        before_fb = _metric_sql(runner,
                                "exchange_spool_fallback_total")
        victim6 = pick_victim()
        preempted = threading.Event()
        pre_lock = threading.Lock()

        def preempt_after_finish(key="", **ctx):
            with pre_lock:
                if not preempted.is_set():
                    wait_stage_finished(victim6, feed_fid)
                    victim6.begin_shutdown()
                    preempted.set()

        FAILPOINTS.configure(
            "exchange.pull", action="callback",
            callback=preempt_after_finish, times=None,
            match=rf":{victim6.port}/v1/task/[^/]*\.{feed_fid}\.\d+$")
        FAILPOINTS.configure(
            "exchange.pull", action="sleep", sleep_s=1.0, times=None,
            match=rf":{victim6.port}/v1/task/[^/]*\.{feed_fid}\.\d+$")
        _assert_rows_equal(runner.execute(query).rows, want,
                           "preemption_storm")
        FAILPOINTS.clear()
        preemptions = [1]
        storm_stop = threading.Event()
        rng = _random.Random(0xE1A57)

        def storm() -> None:
            # expovariate inter-arrivals = Poisson preemption process;
            # never preempt below two live workers (a real preemptible
            # pool has a floor too — the autoscaler's min_workers)
            while not storm_stop.wait(rng.expovariate(1 / 0.5)):
                lw = live_workers()
                if len(lw) < 2:
                    continue
                v = max(lw, key=lambda w: f"http://127.0.0.1:{w.port}")
                v.begin_shutdown()
                preemptions[0] += 1
                add_worker()

        st = threading.Thread(target=storm, daemon=True)
        st.start()
        storm_queries = 1
        storm_deadline = time.time() + 60.0
        try:
            # preemption-bounded, not query-bounded: on a fully warm
            # cluster a fixed query budget can drain before 3 Poisson
            # arrivals land — keep the load going until the storm has
            # actually stormed (the wall-clock cap guards a wedged
            # storm thread, ~1.5s expected at the 0.5s mean cadence)
            while (storm_queries < 5 or preemptions[0] < 3) \
                    and time.time() < storm_deadline:
                _assert_rows_equal(runner.execute(query).rows, want,
                                   "preemption_storm")
                storm_queries += 1
        finally:
            storm_stop.set()
            st.join(timeout=5)
        while len(live_workers()) < 3:
            add_worker()
        replays = _metric_sql(
            runner, "spool_replayed_task_total") - before_replay
        fallbacks = _metric_sql(
            runner, "exchange_spool_fallback_total") - before_fb
        assert preemptions[0] >= 3, \
            f"storm landed only {preemptions[0]} preemptions"
        assert replays >= 1, \
            "no preempted worker's output was replayed from the spool"
        finish(queries=storm_queries, preemptions=preemptions[0],
               spool_replays=replays, spool_fallbacks=fallbacks)

        # -- (k) scale to zero: the worker set vanishes ENTIRELY -------
        # mid-shuffle with the spool on the OBJECT-STORE backend
        # (latency-modeled GCS/S3 stand-in): every worker is killed
        # after the source stage committed, two FRESH workers join,
        # and the query completes row-exact — shuffle state outlived
        # the entire worker set because it lives in the object store,
        # not on any worker's disk
        import shutil as _shutil
        import tempfile as _tempfile
        finish = scenario("scale_to_zero")
        obj_dir = _tempfile.mkdtemp(prefix="chaos-objspool-")
        SPOOL.configure(backend="object", object_dir=obj_dir,
                        object_put_latency_s=0.002,
                        object_get_latency_s=0.002)
        try:
            before = _metric_sql(runner, "task_retry_total")
            before_replay = _metric_sql(runner,
                                        "spool_replayed_task_total")
            before_put = _metric_sql(runner, "spool_object_put_total")
            before_get = _metric_sql(runner, "spool_object_get_total")
            wiped = threading.Event()
            wipe_lock = threading.Lock()

            def wipe(key="", **ctx):
                with wipe_lock:
                    if wiped.is_set():
                        return
                    lw = live_workers()
                    deadline = time.time() + 30.0
                    while time.time() < deadline:
                        src = [t for w in lw
                               for t in list(w.tasks.values())
                               if t.task_id.split(".")[1]
                               == str(source_fid)]
                        if src and all(t.state == "FINISHED"
                                       for t in src):
                            break
                        time.sleep(0.05)
                    else:
                        raise AssertionError(
                            "source stage never committed before "
                            "the wipe")
                    for w in lw:
                        kill_worker(w)
                    add_worker()
                    add_worker()
                    wiped.set()

            FAILPOINTS.configure(
                "exchange.pull", action="callback", callback=wipe,
                times=None,
                match=rf"/v1/task/[^/]*\.{source_fid}\.\d+$")
            _assert_rows_equal(runner.execute(query).rows, want,
                               "scale_to_zero")
            FAILPOINTS.clear()
            assert wiped.is_set(), \
                "the wipe callback never fired"
            replays = _metric_sql(
                runner, "spool_replayed_task_total") - before_replay
            retries = _metric_sql(runner, "task_retry_total") - before
            puts = _metric_sql(
                runner, "spool_object_put_total") - before_put
            gets = _metric_sql(
                runner, "spool_object_get_total") - before_get
            assert replays >= 1, \
                "no source task was preserved across the wipe"
            assert retries >= 1, \
                "no downstream task was re-created on fresh workers"
            assert puts >= 1 and gets >= 1, \
                f"object-store spool never moved (puts={puts}, " \
                f"gets={gets})"
            # per-query GC held across the wipe: zero orphaned objects
            obj_orphans = SPOOL.object_store.query_dirs()
            assert not obj_orphans, \
                f"orphaned object-spool queries: {obj_orphans}"
            finish(spool_replays=replays, task_retries=retries,
                   object_puts=puts, object_gets=gets)
        finally:
            FAILPOINTS.clear()
            SPOOL.configure(backend="local")
            _shutil.rmtree(obj_dir, ignore_errors=True)
        while len(live_workers()) < 3:
            add_worker()

        # the retry count is part of the query history record
        res = runner.local.execute(
            "select retries from system.runtime.completed_queries "
            "where mode = 'cluster' order by create_time")
        assert res.rows and any(int(r[0]) >= 1 for r in res.rows), \
            "no completed_queries record carries a retry count"

        # spool GC: after every scenario (successes, kills, drains and
        # fail-fast aborts alike) no per-query spool directory may
        # survive — disk is accounted and returned
        orphans = SPOOL.query_dirs()
        assert not orphans, f"orphaned spool directories: {orphans}"

        # -- (f) typo'd spec rejected at parse time -----------------------
        # a chaos config naming an unregistered site would inject
        # nothing and "pass" every scenario above — the registry must
        # refuse to arm it (exec/failpoints.py SITES validation)
        finish = scenario("failpoint_validation")
        rejected = False
        try:
            FAILPOINTS.configure_from_spec("worker.task_ruin=error")
        except ValueError as e:
            rejected = "unknown failpoint site" in str(e)
        assert rejected, "typo'd failpoint spec was silently accepted"
        finish(rejected=True)

        # bench-style recovery-time summary: the elastic axis pinned
        # as ELASTIC_r*.json, gated by check_bench_regression
        # --kind elastic (all *_ms => lower is better)
        elastic_scenarios = ("worker_death", "spool_replay",
                             "spool_corrupt", "worker_join",
                             "drain_exit", "preemption_storm",
                             "scale_to_zero")
        summary["elastic"] = {
            "metric": "elastic_recovery_ms",
            "value": round(sum(
                summary["scenarios"][s]["elapsed_s"]
                for s in elastic_scenarios) * 1e3, 1),
            "sub_metrics": [
                {"metric": f"{s}_ms",
                 "value": round(
                     summary["scenarios"][s]["elapsed_s"] * 1e3, 1)}
                for s in elastic_scenarios],
        }
        summary["ok"] = True
        return summary
    finally:
        FAILPOINTS.clear()
        for w in workers:
            try:
                w.stop()
            except Exception:
                pass


def run_fleet_chaos(sf: float = 0.01, coordinators: int = 3,
                    clients: int = 2, per_client: int = 3,
                    verbose: bool = False) -> dict:
    """Coordinator-death drill (ISSUE 19): an in-process fleet of
    ``coordinators`` statement servers over ONE shared worker pool,
    killed down to survivors mid-run.

    Asserts the fleet contract end to end: ZERO failed queries (the
    FleetClient re-dispatches around the corpse), the survivors drop
    the dead coordinator's federated resource-group counts once its
    heartbeats age past the staleness grace, and the loss is
    observable — ``coordinator_lost_total`` read back over plain SQL
    from a survivor."""
    from presto_tpu.client import FleetClient
    from presto_tpu.exec.cluster import ClusterRunner
    from presto_tpu.exec.discovery import DiscoveryNodeManager
    from presto_tpu.exec.failpoints import FAILPOINTS
    from presto_tpu.server.protocol import PrestoTpuServer
    from presto_tpu.server.worker import WorkerServer

    def log(msg: str) -> None:
        if verbose:
            print(msg, file=sys.stderr, flush=True)

    groups = {
        "rootGroups": [
            {"name": "serving", "hardConcurrencyLimit": 8,
             "maxQueued": 1000}],
        "selectors": [{"group": "serving"}]}

    # one shared discovery plane = one shared worker pool: every
    # coordinator's scheduler reads the same membership
    discovery = DiscoveryNodeManager(ttl_s=3600.0)
    worker = WorkerServer(tpch_sf=sf)
    worker.start()
    discovery.announce(worker.node_id,
                       f"http://127.0.0.1:{worker.port}")

    servers = []
    summary: dict = {"sf": sf, "coordinators": coordinators,
                     "scenarios": {}}
    FAILPOINTS.clear()
    try:
        for i in range(coordinators):
            runner = ClusterRunner(tpch_sf=sf, heartbeat=False,
                                   discovery=discovery)
            srv = PrestoTpuServer(runner, resource_groups=groups,
                                  discovery=discovery)
            srv.start()
            servers.append(srv)
        urls = [f"http://127.0.0.1:{s.port}" for s in servers]
        for i, srv in enumerate(servers):
            srv.enable_fleet(
                f"coord-{i}",
                peers=[u for j, u in enumerate(urls) if j != i],
                heartbeat_s=0.2, staleness_grace_s=0.6)
        victim_idx = coordinators - 1
        victim_id = f"coord-{victim_idx}"

        # the kill only means something once the victim's heartbeats
        # are IN every survivor's federated admission view
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if all(victim_id in s.fleet.status()["remote"]
                   for s in servers[:victim_idx]):
                break
            time.sleep(0.05)
        else:
            raise AssertionError(
                "victim heartbeats never reached the survivors")

        # warm every coordinator once (round-robin covers the fleet)
        # and take the fault-free reference rows
        warm = FleetClient(urls, user="fleet-chaos")
        want = warm.execute(QUERY).rows
        for _ in range(coordinators - 1):
            _assert_rows_equal(warm.execute(QUERY).rows, want,
                               "fleet_warmup")
        warm.close()
        log(f"fleet warm: {len(want)} rows via {coordinators} "
            f"coordinators")

        t0 = time.perf_counter()
        total = clients * per_client
        kill_after = max(1, total // 3)
        done = [0]
        count_lock = threading.Lock()
        killed = threading.Event()
        errors: list = []
        fleet_clients = []

        kill_gate = threading.Lock()

        def ensure_killed() -> None:
            # inline, checked by every client BEFORE each dispatch:
            # once the statement count passes the threshold, the kill
            # happens-before every remaining dispatch — and the ring
            # rotation guarantees at least one of those dispatches
            # lands on the victim's slot, so a failover is observed in
            # EVERY interleaving. (A polling killer thread can lose
            # the race outright on a loaded host: a handful of warm
            # statements finish inside its sleep quantum and the kill
            # arrives after the last query.)
            if killed.is_set():
                return
            with count_lock:
                due = done[0] >= kill_after
            if due:
                with kill_gate:
                    if not killed.is_set():
                        log(f"killing {victim_id} after {done[0]} "
                            f"statements")
                        servers[victim_idx].kill()
                        killed.set()

        def client_run(ci: int) -> None:
            fc = FleetClient(urls, user="fleet-chaos")
            fleet_clients.append(fc)
            for _ in range(per_client):
                ensure_killed()
                try:
                    res = fc.execute(QUERY)
                    _assert_rows_equal(res.rows, want,
                                       "coordinator_kill")
                except Exception as e:        # noqa: BLE001
                    errors.append(f"client {ci}: {e!r}")
                with count_lock:
                    done[0] += 1

        threads = [threading.Thread(target=client_run, args=(ci,))
                   for ci in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert killed.is_set(), "the kill threshold was never reached"
        assert not errors, f"queries failed across the kill: {errors}"

        # deterministic failover probe: one more statement whose ring
        # STARTS at the corpse. The concurrent phase proves zero
        # failed queries, but its clients may all have drawn their
        # victim-slot visit BEFORE the kill (the rotation is staggered
        # per client, not per statement outcome) — this probe pins the
        # re-dispatch-around-a-dead-coordinator path in every run.
        probe = FleetClient(urls, user="fleet-chaos")
        probe._rr = victim_idx
        fleet_clients.append(probe)
        _assert_rows_equal(probe.execute(QUERY).rows, want,
                           "failover_probe")
        probe.close()
        total += 1

        # survivors absorb the loss: the dead coordinator ages out of
        # the federated admission view after the staleness grace and
        # lands in the lost ledger; the counter is SQL-visible
        deadline = time.time() + 10.0
        absorbed = False
        lost_seen = 0.0
        views = []
        while time.time() < deadline:
            views = [s.fleet.status()
                     for s in servers[:victim_idx]]
            absorbed = all(
                victim_id in v["lost"]
                and victim_id not in v["remote"] for v in views)
            lost_seen = _metric_sql(servers[0].runner,
                                    "coordinator_lost_total")
            if absorbed and lost_seen >= 1.0:
                break
            time.sleep(0.1)
        assert absorbed, \
            f"survivors still count the dead coordinator: {views}"
        assert lost_seen >= 1.0, \
            "coordinator_lost_total never moved"

        summary["scenarios"]["coordinator_kill"] = {
            "elapsed_s": round(time.perf_counter() - t0, 3),
            "queries": total,
            "failed": len(errors),
            "failovers": sum(fc.failovers_total
                             for fc in fleet_clients),
            "retries": sum(fc.retries_total for fc in fleet_clients),
            "coordinator_lost_total": lost_seen,
            "survivor_lost_view": sorted(views[0]["lost"]),
        }
        log(f"coordinator_kill: "
            f"{summary['scenarios']['coordinator_kill']}")
        summary["ok"] = True
        return summary
    finally:
        FAILPOINTS.clear()
        for srv in servers:
            try:
                srv.kill()
            except Exception:
                pass
        try:
            worker.stop()
        except Exception:
            pass


def run_elastic_ramp(sf: float = 0.02, phases=(1, 3, 1),
                     phase_s: float = 8.0, clients: int = 4,
                     device_floor_ms: float = 60.0,
                     rows_per_batch: int = 16384,
                     verbose: bool = False) -> dict:
    """Load-ramp bench (ISSUE 20): sustained client load while the
    worker pool scales 1 -> N -> 1 through the autoscaler's node
    plane.

    Workers are REAL subprocesses (``LocalProcessProvider`` — the same
    provider the config-driven autoscaler boots), announcing to an
    in-process coordinator over HTTP and sharing one spool directory;
    scale-down is always the drain path (SHUTTING_DOWN -> spool commit
    -> explicit deregister -> process exit), never a kill. The pinned
    claims, gated by ``check_bench_regression --kind elastic``:

    - throughput TRACKS the ramp: peak-N QPS >= 1.5x the 1-worker
      floor (elasticity that doesn't move throughput is a no-op);
    - ZERO failed queries across every transition, drains included;
    - the pool really returns to 1 (the scale-down is exercised under
      load, not just the scale-up).

    ``device_floor_ms`` sets ``PRESTO_TPU_DEVICE_FLOOR_MS`` on the
    WORKER processes: a fixed-throughput device model (each quantum —
    and each SCANNED batch, ``taskexec.device_floor_pad`` — holds the
    device at least that long), making per-worker capacity the
    bottleneck. CI hosts offer a single core to the whole
    multi-process cluster, so real compute cannot overlap across
    workers there — the modeled floor is what makes "QPS tracks the
    worker count" a property of the SYSTEM under test (scheduling,
    drains, exchange) instead of the host's core count.
    ``rows_per_batch`` is lowered so a query scans many batches and
    the modeled work can actually spread across the pool; the query is
    ``RAMP_QUERY`` (a selective scan) for the same reason."""
    import shutil
    import tempfile

    from presto_tpu.client import StatementClient
    from presto_tpu.exec.autoscale import LocalProcessProvider
    from presto_tpu.exec.cluster import ClusterRunner
    from presto_tpu.exec.discovery import DiscoveryNodeManager
    from presto_tpu.exec.spool import SPOOL
    from presto_tpu.server.protocol import PrestoTpuServer

    def log(msg: str) -> None:
        if verbose:
            print(msg, file=sys.stderr, flush=True)

    assert phases and phases[0] == 1 and phases[-1] == 1 \
        and max(phases) > 1, \
        "ramp must go 1 -> N -> 1 (the scale-DOWN is part of the claim)"

    groups = {
        "rootGroups": [
            {"name": "ramp", "hardConcurrencyLimit": 8,
             "maxQueued": 10000}],
        "selectors": [{"group": "ramp"}]}

    # one shared spool dir: drained workers' committed output must be
    # replayable by the survivors (and probeable by the coordinator's
    # preservation check) across process boundaries
    spool_dir = tempfile.mkdtemp(prefix="ramp-spool-")
    SPOOL.configure(directory=spool_dir)
    discovery = DiscoveryNodeManager(ttl_s=3600.0)
    runner = ClusterRunner(tpch_sf=sf, heartbeat=False,
                           discovery=discovery,
                           rows_per_batch=rows_per_batch)
    srv = PrestoTpuServer(runner, resource_groups=groups,
                          discovery=discovery)
    srv.start()
    url = f"http://127.0.0.1:{srv.port}"
    provider = LocalProcessProvider(
        [url], tpch_sf=sf, spool_dir=spool_dir,
        extra_env={"PRESTO_TPU_DEVICE_FLOOR_MS":
                   str(device_floor_ms)} if device_floor_ms else None)

    stop_evt = threading.Event()
    count_lock = threading.Lock()
    completed = [0]
    errors: list = []
    warm = None

    def set_workers(target: int, timeout_s: float = 120.0) -> None:
        """Converge the pool to ``target`` — launches for scale-up,
        the drain path for scale-down — then wait until the
        coordinator's discovery view agrees (drained workers leave by
        explicit GONE deregistration, so membership is prompt)."""
        while len(provider.nodes()) < target:
            h = provider.launch()
            log(f"ramp: launched {h.node_id}")
        while len(provider.nodes()) > target:
            h = provider.nodes()[-1]
            log(f"ramp: draining {h.node_id}")
            assert provider.drain(h, timeout_s=timeout_s), \
                f"worker {h.node_id} did not drain out"
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if len(discovery.active_urls()) == target:
                return
            time.sleep(0.05)
        raise AssertionError(
            f"discovery never converged to {target} workers: "
            f"{discovery.nodes()}")

    def client_run(ci: int) -> None:
        sc = StatementClient(url, user="ramp")
        try:
            while not stop_evt.is_set():
                try:
                    res = sc.execute(RAMP_QUERY)
                    _assert_rows_equal(res.rows, want, "ramp")
                except Exception as e:          # noqa: BLE001
                    if stop_evt.is_set():
                        return
                    errors.append(f"client {ci}: {e!r}")
                    return
                with count_lock:
                    completed[0] += 1
        finally:
            sc.close()

    threads: list = []
    try:
        # floor worker + fault-free reference rows before any load
        set_workers(1)
        warm = StatementClient(url, user="ramp")
        want = warm.execute(RAMP_QUERY).rows
        log(f"ramp: reference {len(want)} rows via 1 worker")

        threads = [threading.Thread(target=client_run, args=(ci,),
                                    daemon=True)
                   for ci in range(clients)]
        for t in threads:
            t.start()

        phase_rows = []
        for target in phases:
            set_workers(target)        # transition happens UNDER load
            # absorb cold compile on freshly launched workers BEFORE
            # the measurement window opens: a new worker's first query
            # JIT-compiles for ~seconds, which is provisioning latency,
            # not steady-state throughput — the claim under test
            for _ in range(2):
                _assert_rows_equal(warm.execute(RAMP_QUERY).rows,
                                   want, "ramp-warmup")
            with count_lock:
                c0, e0 = completed[0], len(errors)
            t0 = time.perf_counter()
            time.sleep(phase_s)
            with count_lock:
                c1, e1 = completed[0], len(errors)
            w = time.perf_counter() - t0
            phase_rows.append({
                "workers": target,
                "queries": c1 - c0,
                "failed": e1 - e0,
                "qps": round((c1 - c0) / w, 2),
                "window_s": round(w, 2)})
            log(f"ramp: phase {phase_rows[-1]}")

        stop_evt.set()
        for t in threads:
            t.join(timeout=30)
        assert not errors, f"queries failed across the ramp: {errors}"

        floor = phase_rows[0]["qps"]
        peak = max(r["qps"] for r in phase_rows
                   if r["workers"] == max(phases))
        ratio = round(peak / floor, 3) if floor > 0 else 0.0
        ramp = {"sf": sf, "clients": clients,
                "device_floor_ms": device_floor_ms,
                "phases": phase_rows, "peak_over_floor": ratio}
        assert ratio >= 1.5, \
            (f"peak QPS {peak} is only {ratio}x the 1-worker floor "
             f"{floor} (need >= 1.5x): {phase_rows}")
        return ramp
    finally:
        stop_evt.set()
        for t in threads:
            t.join(timeout=10)
        try:
            warm.close()
        except Exception:
            pass
        try:
            srv.kill()
        except Exception:
            pass
        provider.stop_all()
        shutil.rmtree(spool_dir, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sf", type=float, default=0.01,
                    help="TPC-H scale factor (default 0.01)")
    ap.add_argument("--fleet", action="store_true",
                    help="run the coordinator-fleet death drill "
                         "instead of the worker chaos suite")
    ap.add_argument("-q", "--quiet", action="store_true")
    ap.add_argument("--ramp", action="store_true",
                    help="additionally run the 1 -> N -> 1 load-ramp "
                         "bench (subprocess workers) and attach its "
                         "block to the elastic summary")
    ap.add_argument("--elastic-out", default=os.environ.get(
        "ELASTIC_OUT"), metavar="PATH",
        help="write the elastic recovery-time summary (bench format) "
             "for check_bench_regression --kind elastic")
    args = ap.parse_args(argv)
    if args.fleet:
        summary = run_fleet_chaos(sf=args.sf, verbose=not args.quiet)
        print(json.dumps(summary, indent=2))
        return 0 if summary.get("ok") else 1
    summary = run_chaos(sf=args.sf, verbose=not args.quiet)
    if args.ramp and summary.get("elastic"):
        summary["elastic"]["ramp"] = run_elastic_ramp(
            verbose=not args.quiet)
    print(json.dumps(summary, indent=2))
    if args.elastic_out and summary.get("elastic"):
        tmp = args.elastic_out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(summary["elastic"], f, indent=2)
        os.replace(tmp, args.elastic_out)
    return 0 if summary.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Chaos smoke: an elastic in-process cluster under seeded failpoints.

Drives every recovery path of the fault-tolerance + spooled-exchange
layers (presto_tpu/exec/cluster.py, exec/spool.py, exec/failpoints.py)
without a real multi-host TPU cluster, and asserts ROW-EXACT parity
with the fault-free run after each injected fault:

- ``task_failure``   — one task FAILs at start (``worker.task_run``
  error); the coordinator re-creates it on a healthy worker.
- ``exchange_drop``  — one exchange pull dies mid-stream
  (``exchange.pull`` error); the ExchangeFailedError names the upstream
  attempt and the retry layer replaces exactly that producer.
- ``straggler``      — one source task sleeps 15s (``worker.task_run``
  sleep); the StageMonitor flags it, a speculative duplicate launches
  on another node and wins, the loser is aborted.
- ``retry_none``     — same task fault under ``retry_policy=NONE``
  fails fast (the pre-fault-tolerance behavior, still available).
- ``worker_death``   — a failpoint callback kills one worker's HTTP
  server mid-query; its tasks (same deterministic splits) reschedule
  onto the survivors.
- ``spool_replay``   — a worker is killed AFTER its source task
  committed its spool, mid-shuffle: consumers replay the pages from
  the durable spool and the source task is NOT re-executed (asserted
  via the task-attempt/retry events — the spooled-exchange headline).
- ``spool_corrupt``  — one spooled page is corrupted on disk
  (``spool.corrupt``) and its worker killed: the checksum catches it,
  the consumer's failure names the upstream, and the retry layer
  re-runs exactly that producer; results stay row-exact.
- ``worker_join``    — a FRESH worker boots and announces mid-query
  while another dies: the re-created tasks land on the late joiner
  (elastic scale-out under the discovery + recovery machinery).
- ``drain_exit``     — a worker is put into SHUTTING_DOWN mid-query
  while the root is still reading its output: it exits within its
  drain grace (no lingering until downstream completion) and the
  consumer finishes from the spool, with zero task retries.

Recovery is asserted observable: ``task_retry_total``,
``speculative_won_total``, ``spool_replayed_task_total``,
``exchange_spool_fallback_total`` and ``node_joined_total`` move, via
``system.runtime.metrics`` over plain SQL; at the end the spool
directory must hold ZERO orphaned per-query directories.

Run directly (prints a JSON summary) or from the tier-1 suite
(tests/test_chaos.py):

    JAX_PLATFORMS=cpu python tools/chaos_smoke.py [--sf 0.01]

``--elastic-out PATH`` (or the ``ELASTIC_OUT`` env var) additionally
writes a bench-style summary of per-scenario recovery times, gated by
``tools/check_bench_regression.py --kind elastic`` against the
committed ``ELASTIC_r*.json``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

QUERY = ("select l_returnflag, l_linestatus, count(*) c, "
         "sum(l_quantity) q, sum(l_extendedprice) e from lineitem "
         "where l_shipdate <= date '1998-09-02' "
         "group by 1, 2 order by 1, 2")


def _metric_sql(runner, name: str) -> float:
    res = runner.local.execute(
        "select value from system.runtime.metrics "
        f"where name = '{name}'")
    return float(res.rows[0][0]) if res.rows else 0.0


def _assert_rows_equal(got, want, scenario: str) -> None:
    assert len(got) == len(want), \
        f"{scenario}: {len(got)} rows vs {len(want)}"
    for gr, wr in zip(got, want):
        for gv, wv in zip(gr, wr):
            if isinstance(wv, float):
                # partial-agg pages merge in arrival order; float sums
                # are reproducible only to rounding, like test_cluster
                assert abs(gv - wv) <= max(abs(wv), 1.0) * 1e-6, \
                    (scenario, gr, wr)
            else:
                assert gv == wv, (scenario, gr, wr)


def run_chaos(sf: float = 0.01, query: str = QUERY,
              verbose: bool = False) -> dict:
    from presto_tpu.exec.cluster import ClusterRunner, QueryFailedError
    from presto_tpu.exec.discovery import DiscoveryNodeManager
    from presto_tpu.exec.failpoints import FAILPOINTS
    from presto_tpu.exec.spool import SPOOL
    from presto_tpu.server.worker import WorkerServer

    def log(msg: str) -> None:
        if verbose:
            print(msg, file=sys.stderr, flush=True)

    # discovery-fed membership (not a static URL list): workers may
    # join or leave mid-query — the elastic half of the smoke
    discovery = DiscoveryNodeManager(ttl_s=3600.0)
    workers = []

    def add_worker() -> WorkerServer:
        w = WorkerServer(tpch_sf=sf, drain_grace_s=2.0)
        w.start()
        workers.append(w)
        discovery.announce(w.node_id, f"http://127.0.0.1:{w.port}")
        return w

    def kill_worker(w: WorkerServer) -> None:
        """In-process stand-in for a worker process death: the network
        surface goes away AND its task threads stop burning the shared
        device scheduler."""
        w.httpd.shutdown()
        w.httpd.server_close()
        for t in list(w.tasks.values()):
            t.abort()

    for _ in range(3):
        add_worker()
    runner = ClusterRunner(tpch_sf=sf, heartbeat=False,
                           discovery=discovery)
    summary: dict = {"sf": sf, "scenarios": {}}
    FAILPOINTS.clear()
    try:
        # fault-free reference (first run also warms the jit caches so
        # fault-run timings measure recovery, not compilation)
        t0 = time.perf_counter()
        want = runner.execute(query).rows
        runner.execute(query)
        summary["baseline_s"] = round(time.perf_counter() - t0, 3)
        log(f"baseline: {len(want)} rows in {summary['baseline_s']}s")

        def scenario(name: str):
            t = time.perf_counter()

            def finish(**extra):
                FAILPOINTS.clear()
                summary["scenarios"][name] = {
                    "elapsed_s": round(time.perf_counter() - t, 3),
                    **extra}
                log(f"{name}: ok {summary['scenarios'][name]}")
            return finish

        # -- (a) one task failure -> task-level retry ---------------------
        finish = scenario("task_failure")
        before = _metric_sql(runner, "task_retry_total")
        FAILPOINTS.configure("worker.task_run", action="error",
                             message="chaos: task failure", times=1)
        _assert_rows_equal(runner.execute(query).rows, want,
                           "task_failure")
        retries = _metric_sql(runner, "task_retry_total") - before
        assert retries >= 1, "task failure did not trigger a retry"
        finish(task_retries=retries)

        # -- (b) exchange drop mid-stream -> upstream replaced ------------
        finish = scenario("exchange_drop")
        before = _metric_sql(runner, "task_retry_total")
        FAILPOINTS.configure("exchange.pull", action="error",
                             message="chaos: exchange drop", times=1)
        _assert_rows_equal(runner.execute(query).rows, want,
                           "exchange_drop")
        retries = _metric_sql(runner, "task_retry_total") - before
        assert retries >= 1, "exchange drop did not trigger a retry"
        finish(task_retries=retries)

        # -- (c) 10x straggler -> speculative attempt wins ----------------
        finish = scenario("straggler")
        before = _metric_sql(runner, "speculative_won_total")
        # partition 0 of the source stage sleeps far past the stage
        # median; attempt suffixes keep the duplicate out of the rule
        FAILPOINTS.configure("worker.task_run", action="sleep",
                             sleep_s=15.0, match=r"\.0\.0@", times=1)
        _assert_rows_equal(runner.execute(query).rows, want,
                           "straggler")
        won = _metric_sql(runner, "speculative_won_total") - before
        assert won >= 1, "straggler did not produce a speculative win"
        finish(speculative_won=won)

        # -- (d) retry_policy=NONE fails fast -----------------------------
        finish = scenario("retry_none")
        FAILPOINTS.configure("worker.task_run", action="error",
                             message="chaos: fail fast", times=1)
        runner.session.properties["retry_policy"] = "NONE"
        try:
            failed = False
            try:
                runner.execute(query)
            except QueryFailedError as e:
                failed = True
                assert "chaos: fail fast" in str(e), str(e)
            assert failed, "retry_policy=NONE still recovered"
        finally:
            del runner.session.properties["retry_policy"]
        finish()

        # -- (e) worker death mid-query -> reschedule on survivors --------
        finish = scenario("worker_death")
        before = _metric_sql(runner, "task_retry_total")
        victim = workers[-1]

        def kill(key="", **ctx):
            kill_worker(victim)

        FAILPOINTS.configure("worker.task_run", action="callback",
                             callback=kill, times=1,
                             match=f"@{victim.node_id}$")
        _assert_rows_equal(runner.execute(query).rows, want,
                           "worker_death")
        retries = _metric_sql(runner, "task_retry_total") - before
        assert retries >= 1, "worker death did not trigger a retry"
        # the dead node must be out of the schedulable set now
        assert f"http://127.0.0.1:{victim.port}" \
            not in runner._schedulable_workers()
        finish(task_retries=retries)
        add_worker()               # replenish the pool to 3 live nodes

        # fragment ids of the smoke query (the scenarios below target
        # the source stage's tasks / the stage the root consumes)
        from presto_tpu.planner.fragmenter import fragment_plan
        from presto_tpu.planner.plan import RemoteSourceNode
        fp = fragment_plan(runner.local.plan(query).root)
        source_fid = next(f.id for f in fp.fragments
                          if f.partitioning == "source")

        def _nodes(n):
            yield n
            for c in n.children:
                yield from _nodes(c)
        feed_fid = next(fid for node in _nodes(fp.root.root)
                        if isinstance(node, RemoteSourceNode)
                        for fid in node.fragment_ids)

        def live_workers():
            return [w for w in workers if w.httpd.socket.fileno() != -1
                    and not w.shutting_down]

        def pick_victim():
            # the single (root) fragment lands on the first worker of
            # the schedulable sweep (sorted by URL): the max-URL live
            # worker can never host the root, which keeps the
            # drain/kill scenarios' retry accounting deterministic
            return max(live_workers(),
                       key=lambda w: f"http://127.0.0.1:{w.port}")

        def wait_stage_finished(w: WorkerServer, fid: int,
                                timeout_s: float = 30.0) -> None:
            deadline = time.time() + timeout_s
            while time.time() < deadline:
                ts = [t for t in list(w.tasks.values())
                      if t.task_id.split(".")[1] == str(fid)]
                if ts and all(t.state == "FINISHED" for t in ts):
                    return
                time.sleep(0.05)
            raise AssertionError(
                f"stage {fid} on {w.node_id} never finished")

        # -- (f) spool replay: kill a worker AFTER its source task ------
        # committed its spool, mid-shuffle. Consumers replay the pages
        # from the durable spool; the source task is NOT re-executed.
        finish = scenario("spool_replay")
        before = _metric_sql(runner, "task_retry_total")
        before_replay = _metric_sql(runner, "spool_replayed_task_total")
        before_fb = _metric_sql(runner,
                                "exchange_spool_fallback_total")
        victim2 = pick_victim()
        killed = threading.Event()
        kill_lock = threading.Lock()

        def kill_after_spool(key="", **ctx):
            # EVERY pull of the victim's source task funnels through
            # here (times unlimited): no page is ever served live, so
            # every consumer must replay from the spool — and the kill
            # only lands once the spool is committed
            with kill_lock:
                if not killed.is_set():
                    wait_stage_finished(victim2, source_fid)
                    kill_worker(victim2)
                    killed.set()

        FAILPOINTS.configure(
            "exchange.pull", action="callback",
            callback=kill_after_spool, times=None,
            match=rf":{victim2.port}/v1/task/[^/]*\.{source_fid}\.\d+$")
        _assert_rows_equal(runner.execute(query).rows, want,
                           "spool_replay")
        FAILPOINTS.clear()
        replays = _metric_sql(
            runner, "spool_replayed_task_total") - before_replay
        fallbacks = _metric_sql(
            runner, "exchange_spool_fallback_total") - before_fb
        retries = _metric_sql(runner, "task_retry_total") - before
        assert replays >= 1, \
            "lost-but-spooled task was not preserved"
        assert fallbacks >= 1, \
            "no consumer replayed from the spool"
        # the headline assertion: NO source-stage task was re-executed
        # (retries are the victim's other tasks — never the producer
        # whose output lives in the spool)
        events = runner._last_run_info.get("events") or []
        source_retries = [
            ev for ev in events if ev.get("kind") == "task_retry"
            and str(ev.get("task", "")).split(".")[1]
            == str(source_fid)]
        assert not source_retries, \
            f"spooled source task was re-executed: {source_retries}"
        finish(spool_replays=replays, spool_fallbacks=fallbacks,
               task_retries=retries)
        add_worker()

        # -- (g) spool corruption: checksum -> retry from upstream ------
        finish = scenario("spool_corrupt")
        before = _metric_sql(runner, "task_retry_total")
        before_cor = _metric_sql(runner, "spool_corruption_total")
        victim3 = pick_victim()
        killed3 = threading.Event()
        kill3_lock = threading.Lock()
        corrupt_armed = threading.Event()

        def arm_corrupt(key="", task_id="", **ctx):
            # corrupt the first spooled page of a source task ON THE
            # VICTIM (the task id is only known once the worker starts
            # it): the frame keeps the original checksum, the payload
            # flips one byte on disk. Arming by exact task id matters:
            # a survivor's corrupted page would be served from the
            # clean in-memory fast path and never detected.
            import re as _re
            if task_id.split(".")[1] == str(source_fid) \
                    and not corrupt_armed.is_set():
                corrupt_armed.set()
                FAILPOINTS.configure(
                    "spool.corrupt", action="error", times=1,
                    match=rf"^{_re.escape(task_id)}/")

        FAILPOINTS.configure("worker.task_run", action="callback",
                             callback=arm_corrupt, times=None,
                             match=f"@{victim3.node_id}$")

        def kill_after_corrupt(key="", **ctx):
            with kill3_lock:
                if not killed3.is_set():
                    wait_stage_finished(victim3, source_fid)
                    kill_worker(victim3)
                    killed3.set()

        FAILPOINTS.configure(
            "exchange.pull", action="callback",
            callback=kill_after_corrupt, times=None,
            match=rf":{victim3.port}/v1/task/[^/]*\.{source_fid}\.\d+$")
        _assert_rows_equal(runner.execute(query).rows, want,
                           "spool_corrupt")
        FAILPOINTS.clear()
        corruptions = _metric_sql(
            runner, "spool_corruption_total") - before_cor
        retries = _metric_sql(runner, "task_retry_total") - before
        assert corrupt_armed.is_set(), \
            "victim never ran a source task to corrupt"
        assert corruptions >= 1, \
            "corrupted spool page was served without detection"
        assert retries >= 1, \
            "spool corruption did not re-run the producer"
        finish(corruptions=corruptions, task_retries=retries)
        add_worker()

        # -- (h) elastic join: a fresh worker boots + announces -------
        # mid-query while another dies; the re-created tasks land on
        # the late joiner
        finish = scenario("worker_join")
        before = _metric_sql(runner, "task_retry_total")
        before_join = _metric_sql(runner, "node_joined_total")
        victim4 = pick_victim()
        joiner: dict = {}

        def kill_and_join(key="", **ctx):
            kill_worker(victim4)
            joiner["w"] = add_worker()

        FAILPOINTS.configure("worker.task_run", action="callback",
                             callback=kill_and_join, times=1,
                             match=f"@{victim4.node_id}$")
        _assert_rows_equal(runner.execute(query).rows, want,
                           "worker_join")
        FAILPOINTS.clear()
        retries = _metric_sql(runner, "task_retry_total") - before
        joined = _metric_sql(runner, "node_joined_total") - before_join
        assert retries >= 1, "worker death did not trigger a retry"
        assert joined >= 1, "the late joiner was never federated"
        joiner_url = f"http://127.0.0.1:{joiner['w'].port}"
        events = runner._last_run_info.get("events") or []
        landed = [ev for ev in events
                  if ev.get("kind") == "task_retry"
                  and ev.get("to") == joiner_url]
        assert landed, \
            f"no re-created task landed on the late joiner: {events}"
        finish(task_retries=retries, joined=joined,
               landed_on_joiner=len(landed))

        # -- (i) drain-and-exit: SHUTTING_DOWN mid-read ----------------
        # the worker exits within its drain grace while the root is
        # still consuming its output; the root finishes from the spool
        # with ZERO task retries
        finish = scenario("drain_exit")
        before = _metric_sql(runner, "task_retry_total")
        before_fb = _metric_sql(runner,
                                "exchange_spool_fallback_total")
        victim5 = pick_victim()
        drained = threading.Event()
        drain_lock = threading.Lock()

        def drain_after_finish(key="", **ctx):
            with drain_lock:
                if not drained.is_set():
                    wait_stage_finished(victim5, feed_fid)
                    victim5.begin_shutdown()
                    drained.set()

        # the root's pulls of the victim's feed-stage task trigger the
        # drain (once that task finished), then slow to one page per
        # second — guaranteeing the worker is GONE before the root
        # drains the buffer, so the tail must come from the spool
        FAILPOINTS.configure(
            "exchange.pull", action="callback",
            callback=drain_after_finish, times=None,
            match=rf":{victim5.port}/v1/task/[^/]*\.{feed_fid}\.\d+$")
        FAILPOINTS.configure(
            "exchange.pull", action="sleep", sleep_s=1.0, times=None,
            match=rf":{victim5.port}/v1/task/[^/]*\.{feed_fid}\.\d+$")
        _assert_rows_equal(runner.execute(query).rows, want,
                           "drain_exit")
        FAILPOINTS.clear()
        retries = _metric_sql(runner, "task_retry_total") - before
        fallbacks = _metric_sql(
            runner, "exchange_spool_fallback_total") - before_fb
        assert retries == 0, \
            f"drain caused {retries} retries (spool should replay)"
        assert fallbacks >= 1, \
            "root never replayed the drained worker's output"
        # the drained worker's process actually EXITED within its
        # grace (no lingering until downstream completion): its socket
        # must refuse within a short post-query window
        exit_deadline = time.time() + 5.0
        gone = False
        while time.time() < exit_deadline:
            try:
                import urllib.request
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{victim5.port}/v1/info",
                        timeout=1):
                    pass
            except Exception:
                gone = True
                break
            time.sleep(0.1)
        assert gone, "drained worker lingered past its grace"
        assert f"http://127.0.0.1:{victim5.port}" \
            not in runner._schedulable_workers()
        finish(task_retries=retries, spool_fallbacks=fallbacks)

        # the retry count is part of the query history record
        res = runner.local.execute(
            "select retries from system.runtime.completed_queries "
            "where mode = 'cluster' order by create_time")
        assert res.rows and any(int(r[0]) >= 1 for r in res.rows), \
            "no completed_queries record carries a retry count"

        # spool GC: after every scenario (successes, kills, drains and
        # fail-fast aborts alike) no per-query spool directory may
        # survive — disk is accounted and returned
        orphans = SPOOL.query_dirs()
        assert not orphans, f"orphaned spool directories: {orphans}"

        # -- (f) typo'd spec rejected at parse time -----------------------
        # a chaos config naming an unregistered site would inject
        # nothing and "pass" every scenario above — the registry must
        # refuse to arm it (exec/failpoints.py SITES validation)
        finish = scenario("failpoint_validation")
        rejected = False
        try:
            FAILPOINTS.configure_from_spec("worker.task_ruin=error")
        except ValueError as e:
            rejected = "unknown failpoint site" in str(e)
        assert rejected, "typo'd failpoint spec was silently accepted"
        finish(rejected=True)

        # bench-style recovery-time summary: the elastic axis pinned
        # as ELASTIC_r*.json, gated by check_bench_regression
        # --kind elastic (all *_ms => lower is better)
        elastic_scenarios = ("worker_death", "spool_replay",
                             "spool_corrupt", "worker_join",
                             "drain_exit")
        summary["elastic"] = {
            "metric": "elastic_recovery_ms",
            "value": round(sum(
                summary["scenarios"][s]["elapsed_s"]
                for s in elastic_scenarios) * 1e3, 1),
            "sub_metrics": [
                {"metric": f"{s}_ms",
                 "value": round(
                     summary["scenarios"][s]["elapsed_s"] * 1e3, 1)}
                for s in elastic_scenarios],
        }
        summary["ok"] = True
        return summary
    finally:
        FAILPOINTS.clear()
        for w in workers:
            try:
                w.stop()
            except Exception:
                pass


def run_fleet_chaos(sf: float = 0.01, coordinators: int = 3,
                    clients: int = 2, per_client: int = 3,
                    verbose: bool = False) -> dict:
    """Coordinator-death drill (ISSUE 19): an in-process fleet of
    ``coordinators`` statement servers over ONE shared worker pool,
    killed down to survivors mid-run.

    Asserts the fleet contract end to end: ZERO failed queries (the
    FleetClient re-dispatches around the corpse), the survivors drop
    the dead coordinator's federated resource-group counts once its
    heartbeats age past the staleness grace, and the loss is
    observable — ``coordinator_lost_total`` read back over plain SQL
    from a survivor."""
    from presto_tpu.client import FleetClient
    from presto_tpu.exec.cluster import ClusterRunner
    from presto_tpu.exec.discovery import DiscoveryNodeManager
    from presto_tpu.exec.failpoints import FAILPOINTS
    from presto_tpu.server.protocol import PrestoTpuServer
    from presto_tpu.server.worker import WorkerServer

    def log(msg: str) -> None:
        if verbose:
            print(msg, file=sys.stderr, flush=True)

    groups = {
        "rootGroups": [
            {"name": "serving", "hardConcurrencyLimit": 8,
             "maxQueued": 1000}],
        "selectors": [{"group": "serving"}]}

    # one shared discovery plane = one shared worker pool: every
    # coordinator's scheduler reads the same membership
    discovery = DiscoveryNodeManager(ttl_s=3600.0)
    worker = WorkerServer(tpch_sf=sf)
    worker.start()
    discovery.announce(worker.node_id,
                       f"http://127.0.0.1:{worker.port}")

    servers = []
    summary: dict = {"sf": sf, "coordinators": coordinators,
                     "scenarios": {}}
    FAILPOINTS.clear()
    try:
        for i in range(coordinators):
            runner = ClusterRunner(tpch_sf=sf, heartbeat=False,
                                   discovery=discovery)
            srv = PrestoTpuServer(runner, resource_groups=groups,
                                  discovery=discovery)
            srv.start()
            servers.append(srv)
        urls = [f"http://127.0.0.1:{s.port}" for s in servers]
        for i, srv in enumerate(servers):
            srv.enable_fleet(
                f"coord-{i}",
                peers=[u for j, u in enumerate(urls) if j != i],
                heartbeat_s=0.2, staleness_grace_s=0.6)
        victim_idx = coordinators - 1
        victim_id = f"coord-{victim_idx}"

        # the kill only means something once the victim's heartbeats
        # are IN every survivor's federated admission view
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if all(victim_id in s.fleet.status()["remote"]
                   for s in servers[:victim_idx]):
                break
            time.sleep(0.05)
        else:
            raise AssertionError(
                "victim heartbeats never reached the survivors")

        # warm every coordinator once (round-robin covers the fleet)
        # and take the fault-free reference rows
        warm = FleetClient(urls, user="fleet-chaos")
        want = warm.execute(QUERY).rows
        for _ in range(coordinators - 1):
            _assert_rows_equal(warm.execute(QUERY).rows, want,
                               "fleet_warmup")
        warm.close()
        log(f"fleet warm: {len(want)} rows via {coordinators} "
            f"coordinators")

        t0 = time.perf_counter()
        total = clients * per_client
        kill_after = max(1, total // 3)
        done = [0]
        count_lock = threading.Lock()
        killed = threading.Event()
        errors: list = []
        fleet_clients = []

        def killer() -> None:
            while not killed.is_set():
                with count_lock:
                    n = done[0]
                if n >= kill_after:
                    killed.set()
                    log(f"killing {victim_id} after {n} statements")
                    servers[victim_idx].kill()
                    return
                time.sleep(0.01)

        def client_run(ci: int) -> None:
            fc = FleetClient(urls, user="fleet-chaos")
            fleet_clients.append(fc)
            for _ in range(per_client):
                try:
                    res = fc.execute(QUERY)
                    _assert_rows_equal(res.rows, want,
                                       "coordinator_kill")
                except Exception as e:        # noqa: BLE001
                    errors.append(f"client {ci}: {e!r}")
                with count_lock:
                    done[0] += 1

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        threads = [threading.Thread(target=client_run, args=(ci,))
                   for ci in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        killed.set()
        kt.join(timeout=5)
        assert not errors, f"queries failed across the kill: {errors}"

        # survivors absorb the loss: the dead coordinator ages out of
        # the federated admission view after the staleness grace and
        # lands in the lost ledger; the counter is SQL-visible
        deadline = time.time() + 10.0
        absorbed = False
        lost_seen = 0.0
        views = []
        while time.time() < deadline:
            views = [s.fleet.status()
                     for s in servers[:victim_idx]]
            absorbed = all(
                victim_id in v["lost"]
                and victim_id not in v["remote"] for v in views)
            lost_seen = _metric_sql(servers[0].runner,
                                    "coordinator_lost_total")
            if absorbed and lost_seen >= 1.0:
                break
            time.sleep(0.1)
        assert absorbed, \
            f"survivors still count the dead coordinator: {views}"
        assert lost_seen >= 1.0, \
            "coordinator_lost_total never moved"

        summary["scenarios"]["coordinator_kill"] = {
            "elapsed_s": round(time.perf_counter() - t0, 3),
            "queries": total,
            "failed": len(errors),
            "failovers": sum(fc.failovers_total
                             for fc in fleet_clients),
            "retries": sum(fc.retries_total for fc in fleet_clients),
            "coordinator_lost_total": lost_seen,
            "survivor_lost_view": sorted(views[0]["lost"]),
        }
        log(f"coordinator_kill: "
            f"{summary['scenarios']['coordinator_kill']}")
        summary["ok"] = True
        return summary
    finally:
        FAILPOINTS.clear()
        for srv in servers:
            try:
                srv.kill()
            except Exception:
                pass
        try:
            worker.stop()
        except Exception:
            pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sf", type=float, default=0.01,
                    help="TPC-H scale factor (default 0.01)")
    ap.add_argument("--fleet", action="store_true",
                    help="run the coordinator-fleet death drill "
                         "instead of the worker chaos suite")
    ap.add_argument("-q", "--quiet", action="store_true")
    ap.add_argument("--elastic-out", default=os.environ.get(
        "ELASTIC_OUT"), metavar="PATH",
        help="write the elastic recovery-time summary (bench format) "
             "for check_bench_regression --kind elastic")
    args = ap.parse_args(argv)
    if args.fleet:
        summary = run_fleet_chaos(sf=args.sf, verbose=not args.quiet)
        print(json.dumps(summary, indent=2))
        return 0 if summary.get("ok") else 1
    summary = run_chaos(sf=args.sf, verbose=not args.quiet)
    print(json.dumps(summary, indent=2))
    if args.elastic_out and summary.get("elastic"):
        tmp = args.elastic_out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(summary["elastic"], f, indent=2)
        os.replace(tmp, args.elastic_out)
    return 0 if summary.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())

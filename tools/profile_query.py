#!/usr/bin/env python
"""Device-truth profile of one bench query as machine-readable JSON.

The repeatable path behind "profile q55 and let the cost verdict pick
the fight" (docs/perf.md round 8): runs a bench query — by name (q1,
q3, q55, q27) over the bench harness's connector at ``--sf``, or any
``--sql`` — under the PR 6 profiling plane (``profile`` semantics:
every jit dispatch bracketed with block_until_ready and attributed to
the plan operator whose frame made it) and emits the per-operator
``device_time_s``/``flops``/``hbm_bytes`` table, the executed join
strategies, the executables ranked by device time, and the
input-bound-vs-compute-bound cost verdict as ONE JSON document — so
future perf PRs start from device truth instead of wall-clock guesses.

Usage:
    python -m tools.profile_query --query q55 --sf 1 --out q55_prof.json
    python -m tools.profile_query --catalog tpch --sql "select ..."

The timed run is the SECOND execution (first pays compile + scan
staging, mirroring bench.py's warmup), unless ``--cold`` keeps the
first. Exit 0 on success with the JSON on stdout (and in ``--out``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

#: TPC-H Q3 through the ENGINE SQL path (the bench.py q3 config is a
#: hand pipeline with no SQL text; the gate queries must all be
#: profileable by name)
_TPCH_Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
  o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""

#: named bench queries -> (catalog, bench.py SQL attribute or text)
_NAMED = {
    "q1": ("tpch", "_TPCH_Q1"),
    "q3": ("tpch", _TPCH_Q3),
    "q55": ("tpcds", "_DS_Q55"),
    "q27": ("tpcds", "_DS_Q27"),
}


def _node_rows(plan, stats):
    """Flattened per-operator table, plan order (root first)."""
    from presto_tpu.planner.printer import _label
    rows = []

    def walk(n, depth):
        st = stats.stats_for(n)
        dev = stats.device_for(n)
        js = stats.join_strategy_for(n)
        row = {"depth": depth, "operator": _label(n)}
        if st is not None:
            child_wall = sum(
                (stats.stats_for(c).wall_s
                 if stats.stats_for(c) is not None else 0.0)
                for c in n.children)
            row.update({
                "wall_s": round(st.wall_s, 6),
                "self_s": round(max(st.wall_s - child_wall, 0.0), 6),
                "rows": st.rows, "batches": st.batches,
            })
        if dev is not None:
            row.update({
                "device_time_s": round(dev["device_time_s"], 6),
                "flops": dev["flops"], "hbm_bytes": dev["hbm_bytes"],
            })
        if js is not None:
            row["join_strategy"] = f"{js[0]}/{js[1]}"
        rows.append(row)
        for c in n.children:
            walk(c, depth + 1)

    walk(plan.root, 0)
    return rows


def profile_query(runner, sql: str, warm_runs: int = 1,
                  mesh: "int | None" = None) -> dict:
    """One profiled execution (after ``warm_runs`` untimed warmups) ->
    the JSON document. Importable for tests. ``mesh`` (device count,
    0 = all) runs the query on the SPMD mesh path: per-operator device
    time then also attributes **per shard** (the profiled bracket times
    the whole mesh dispatch, so one shard's share is time/n on a
    balanced stage), and the document gains the fragmenter's mesh-stage
    recipe plus whether the auto-router actually selected the mesh."""
    from presto_tpu.exec.local import execute_plan
    from presto_tpu.exec.stats import StatsCollector
    from presto_tpu.obs.metrics import REGISTRY
    from presto_tpu.obs.profiler import cost_verdict

    n_mesh = None
    session = runner.session
    if mesh is not None:
        import dataclasses as _dc

        from presto_tpu.exec.distributed import mesh_device_count
        # per-call overlay, never the shared session: a later
        # profile_query on the same runner must not silently inherit
        # this call's mesh routing
        session = _dc.replace(
            session,
            properties={**session.properties,
                        "mesh_execution": "auto",
                        "mesh_devices": int(mesh)})
        n_mesh = mesh_device_count(session)

    def selected() -> float:
        return REGISTRY.value("mesh_path_selected_total")

    plan = runner.plan(sql)
    for _ in range(max(warm_runs, 0)):
        execute_plan(plan, session, runner.rows_per_batch,
                     collect_rows=False)
    sel0 = selected()
    stats = StatsCollector(count_rows=True)
    t0 = time.perf_counter()
    execute_plan(plan, session, runner.rows_per_batch, stats=stats,
                 collect_rows=False)
    stats.total_wall_s = time.perf_counter() - t0
    verdict = cost_verdict(stats)
    operators = _node_rows(plan, stats)
    doc = {
        "sql": " ".join(sql.split()),
        "wall_s": round(stats.total_wall_s, 6),
        "backend": _backend(),
        "operators": operators,
        "executables": [
            {k: e[k] for k in ("name", "invocations", "device_time_s",
                               "compile_seconds", "flops",
                               "bytes_accessed")}
            for e in stats.executables_used()],
        "cost_verdict": verdict,
    }
    if mesh is not None:
        on_mesh = selected() > sel0
        if on_mesh and n_mesh:
            for row in operators:
                if "device_time_s" in row:
                    row["device_time_per_shard_s"] = round(
                        row["device_time_s"] / n_mesh, 6)
        from presto_tpu.planner.fragmenter import plan_mesh_stages
        mp = plan_mesh_stages(plan.root)
        doc["mesh"] = {
            "n_devices": n_mesh,
            "selected": on_mesh,
            "supported": mp.supported,
            "stages": [{"id": s.id, "kind": s.kind,
                        "exchange": s.exchange, "keys": list(s.keys),
                        "ops": list(s.ops), "fused": s.fused}
                       for s in mp.stages],
        }
        # flight recorder attribution (obs/flight.py): one command
        # yields both views of a mesh query — per-operator device time
        # above, per-round wall-clock buckets here
        fl = getattr(stats, "mesh_flight", None)
        if fl is not None and fl.attribution is not None:
            doc["mesh"]["attribution"] = fl.attribution
    return doc


def _backend() -> str:
    import jax
    return jax.default_backend()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="profile one bench query; emit per-operator device "
                    "time + cost verdict as JSON")
    ap.add_argument("--query", choices=sorted(_NAMED),
                    help="named bench query (bench.py SQL text)")
    ap.add_argument("--sql", help="arbitrary SQL instead of --query")
    ap.add_argument("--catalog", default=None,
                    help="catalog for --sql (default from --query, "
                         "else tpch)")
    ap.add_argument("--sf", type=float, default=1.0,
                    help="scale factor (default 1)")
    ap.add_argument("--rows-per-batch", type=int, default=1 << 20)
    ap.add_argument("--cold", action="store_true",
                    help="profile the FIRST run (includes compile + "
                         "staging) instead of a warmed run")
    ap.add_argument("--mesh", type=int, default=None, metavar="N",
                    help="profile on an N-device mesh (0 = every "
                         "visible device): per-operator device time "
                         "also attributes per shard, and the document "
                         "gains the mesh-stage recipe. Needs N visible "
                         "devices (set XLA_FLAGS="
                         "--xla_force_host_platform_device_count=N "
                         "for a virtual CPU mesh)")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="also write the JSON here (temp+rename)")
    args = ap.parse_args(argv)

    if bool(args.query) == bool(args.sql):
        print(json.dumps({"error": "exactly one of --query/--sql"}))
        return 2
    if args.query:
        catalog, attr = _NAMED[args.query]
        if attr.startswith("_") and "\n" not in attr:
            import bench
            sql = getattr(bench, attr)
        else:
            sql = attr
    else:
        catalog, sql = args.catalog or "tpch", args.sql

    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.exec.runner import LocalRunner
    catalogs = CatalogManager()
    if catalog == "tpcds":
        from presto_tpu.connectors.tpcds import TpcdsConnector
        catalogs.register("tpcds", TpcdsConnector(sf=args.sf))
    else:
        from presto_tpu.connectors.tpch import TpchConnector
        catalogs.register("tpch", TpchConnector(sf=args.sf))
    runner = LocalRunner(catalogs=catalogs, catalog=catalog,
                         rows_per_batch=args.rows_per_batch)

    doc = profile_query(runner, sql,
                        warm_runs=0 if args.cold else 1,
                        mesh=args.mesh)
    doc["sf"] = args.sf
    text = json.dumps(doc, indent=2, default=str)
    print(text)
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            f.write(text + "\n")
        os.replace(tmp, args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Coordinator-fleet launcher: N statement servers, one worker pool.

The horizontal-serving topology (docs/serving.md "Fleet"): every
coordinator is a full ``PrestoTpuServer`` over a ``ClusterRunner`` —
same caches, same resource groups, same SLO plane — joined into a
fleet via :meth:`PrestoTpuServer.enable_fleet`. Workers announce to
EVERY coordinator (multi-URI ``Announcer``), so the fleet shares one
elastic worker pool through the discovery plane while clients spread
statements across coordinators with ``presto_tpu.client.FleetClient``.

Because coordinator caches are per-process, real horizontal scale
needs real processes (the GIL caps in-process coordinator threads at
~1x): this module is both the subprocess entrypoint and the parent-side
launcher.

Child modes (one process each, stdin-tethered — EOF on stdin is the
orphan kill switch)::

    python -m tools.fleet --serve-coordinator --port P --node-id c0 \
        --peers http://127.0.0.1:P1,http://127.0.0.1:P2 \
        --sf 0.01 --sqlite /tmp/fleet.db --heartbeat-s 0.5
    python -m tools.fleet --serve-worker --port P \
        --coordinators http://127.0.0.1:P0,... --sf 0.01 \
        --sqlite /tmp/fleet.db

Parent API::

    fleet = launch_fleet(n_coordinators=3, sf=0.01, workers=1)
    fleet.urls               # coordinator base URLs
    fleet.metrics(1)         # GET /v1/metrics of coordinator 1
    fleet.slo(1)             # GET /v1/slo of coordinator 1
    fleet.kill_coordinator(0)  # SIGKILL — chaos, no drain
    fleet.stop()

Both the serving bench's fleet mode (``SERVING_COORDINATORS=N
python bench.py serving``) and the fleet chaos drill ride this module.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

#: the serving-bench resource-group config (two weighted tenants, both
#: under SLO): fleet children default to the same shape bench_serving
#: uses standalone, so a fleet bench measures topology — not config —
#: against SERVING_r03
_SLO_SPEC = {"latencyTargetMs": 2000, "latencyObjective": 0.95,
             "availabilityObjective": 0.99}
SERVING_GROUPS = {
    "rootGroups": [
        {"name": "serving", "hardConcurrencyLimit": 8,
         "maxQueued": 10_000,
         "subGroups": [
             {"name": "dash", "hardConcurrencyLimit": 8,
              "schedulingWeight": 2, "slo": dict(_SLO_SPEC)},
             {"name": "adhoc", "hardConcurrencyLimit": 8,
              "schedulingWeight": 1, "slo": dict(_SLO_SPEC)}]}],
    "selectors": [{"user": "dash-.*", "group": "serving.dash"},
                  {"group": "serving.adhoc"}]}


def _enable_compile_cache() -> None:
    """Same persistent XLA cache bench.py uses (jax.config is
    per-process — children must opt in themselves)."""
    import jax
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(repo, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def _build_catalogs(sf: float, sqlite_path: Optional[str]):
    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.connectors.spi import CatalogManager
    from presto_tpu.connectors.system import SystemConnector
    from presto_tpu.connectors.tpch import TpchConnector

    catalogs = CatalogManager()
    catalogs.register("tpch", TpchConnector(sf=sf))
    catalogs.register("memory", MemoryConnector())
    if sqlite_path:
        # the fleet's shared WRITABLE catalog: one database file, every
        # coordinator (and worker) a connection over it. Writes through
        # any coordinator bump its local data_version AND broadcast a
        # fleet bump; sqlite's own PRAGMA data_version backstops missed
        # broadcasts at revalidation time (foreign commits bump it)
        from presto_tpu.connectors.sqlite import SqliteConnector
        catalogs.register("fleetdb", SqliteConnector(sqlite_path))
    catalogs.register("system", SystemConnector(catalogs))
    return catalogs


def _stdin_tether(cleanup) -> None:
    """Block until stdin EOF (parent exit/stop), then clean up. The
    tether makes orphaned children self-terminate instead of leaking
    JAX processes when the parent is SIGKILLed."""
    try:
        while sys.stdin.buffer.read(4096):
            pass
    except OSError:
        pass
    cleanup()


def serve_coordinator(args) -> None:
    _enable_compile_cache()
    from presto_tpu.exec.cluster import ClusterRunner
    from presto_tpu.exec.discovery import DiscoveryNodeManager
    from presto_tpu.obs.timeseries import TIMESERIES
    from presto_tpu.server.protocol import PrestoTpuServer

    catalogs = _build_catalogs(args.sf, args.sqlite)
    discovery = DiscoveryNodeManager()
    runner = ClusterRunner(catalogs=catalogs, discovery=discovery,
                           tpch_sf=args.sf)
    runner.session.properties.update({"plan_template_cache": True,
                                      "result_cache": True})
    groups = (json.loads(args.groups_json) if args.groups_json
              else SERVING_GROUPS)
    # dense sampling: fleet benches are short-walled; the SLO timeline
    # needs real windowed points per phase (same rationale as
    # bench_serving standalone)
    TIMESERIES.configure(sample_interval_s=0.2)
    srv = PrestoTpuServer(runner, port=args.port,
                          resource_groups=groups, discovery=discovery)
    srv.start()
    peers = [u.strip() for u in (args.peers or "").split(",")
             if u.strip()]
    srv.enable_fleet(args.node_id, peers=peers,
                     heartbeat_s=args.heartbeat_s,
                     staleness_grace_s=args.staleness_grace_s or None)
    print(json.dumps({"ok": True, "role": "coordinator",
                      "nodeId": args.node_id,
                      "url": f"http://127.0.0.1:{srv.port}"}),
          flush=True)
    _stdin_tether(srv.stop)


def serve_worker(args) -> None:
    _enable_compile_cache()
    from presto_tpu.server.worker import WorkerServer

    catalogs = _build_catalogs(args.sf, args.sqlite)
    w = WorkerServer(catalogs=catalogs, port=args.port,
                     node_id=args.node_id or None)
    w.start()
    uris = [u.strip() for u in (args.coordinators or "").split(",")
            if u.strip()]
    # announce to EVERY coordinator: one worker pool, fleet-wide. The
    # 1s beat keeps membership fresh well inside discovery's TTL even
    # while coordinators churn
    w.start_announcing(uris, interval_s=1.0)
    print(json.dumps({"ok": True, "role": "worker",
                      "nodeId": w.node_id,
                      "url": f"http://127.0.0.1:{w.port}"}),
          flush=True)
    _stdin_tether(w.stop)


# ---------------------------------------------------------------------------
# parent side
# ---------------------------------------------------------------------------

def _free_ports(n: int) -> List[int]:
    """Reserve n distinct ephemeral ports (bind, record, close). The
    close-to-spawn window is racy in principle; in practice the
    container's ephemeral allocator doesn't re-issue a just-closed port
    before the child binds it."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _get_json(url: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


class FleetHandle:
    """A running fleet: coordinator/worker subprocess records plus the
    scrape and chaos surface the bench and tests drive."""

    def __init__(self, coordinators: List[dict], workers: List[dict],
                 sqlite_path: Optional[str],
                 spawn_cfg: Optional[dict] = None):
        self.coordinators = coordinators   # {proc, url, node_id, port}
        self.workers = workers
        self.sqlite_path = sqlite_path
        #: launch parameters, kept so the coordinator tier can scale
        #: up after launch (autoscaler scale_coordinator decisions)
        self.spawn_cfg = dict(spawn_cfg or {})
        self._coord_seq = len(coordinators)

    @property
    def urls(self) -> List[str]:
        return [c["url"] for c in self.coordinators]

    def live_urls(self) -> List[str]:
        return [c["url"] for c in self.coordinators
                if c["proc"].poll() is None]

    def metrics(self, i: int) -> Dict[str, float]:
        """Scrape coordinator ``i``'s /v1/metrics (Prometheus text) back
        into the registry's dotted-name map: ``fam{key="sub"}`` →
        ``fam.sub``. Samples with structural labels (le/quantile/node)
        are dropped — the fleet bench reads counters."""
        from presto_tpu.obs.exposition import parse_exposition
        url = self.coordinators[i]["url"] + "/v1/metrics"
        with urllib.request.urlopen(url, timeout=10) as r:
            text = r.read().decode("utf-8")
        samples, _types = parse_exposition(text)
        out: Dict[str, float] = {}
        for (name, labels), value in samples.items():
            labels = dict(labels)
            key = labels.pop("key", "")
            if labels:
                continue
            out[f"{name}.{key}" if key else name] = value
        return out

    def slo(self, i: int) -> dict:
        return _get_json(self.coordinators[i]["url"] + "/v1/slo")

    def fleet_status(self, i: int) -> dict:
        return _get_json(self.coordinators[i]["url"] + "/v1/fleet")

    def add_coordinator(self) -> dict:
        """Scale the coordinator tier UP: spawn one more fleet member
        peered with the current live coordinators. Its first heartbeat
        teaches every incumbent its url (dynamic peering,
        serving/fleet.fold_heartbeat), so the newcomer joins the
        broadcast/federation fabric without restarting anyone."""
        cfg = self.spawn_cfg
        node_id = f"coord-{self._coord_seq}"
        self._coord_seq += 1
        (port,) = _free_ports(1)
        argv = ["--serve-coordinator", "--port", str(port),
                "--node-id", node_id,
                "--peers", ",".join(self.live_urls()),
                "--sf", str(cfg.get("sf", 0.01)),
                "--heartbeat-s", str(cfg.get("heartbeat_s", 0.5))]
        if self.sqlite_path:
            argv += ["--sqlite", self.sqlite_path]
        if cfg.get("staleness_grace_s"):
            argv += ["--staleness-grace-s",
                     str(cfg["staleness_grace_s"])]
        if cfg.get("groups"):
            argv += ["--groups-json", json.dumps(cfg["groups"])]
        rec = {"proc": _spawn(argv), "node_id": node_id, "port": port,
               "url": f"http://127.0.0.1:{port}"}
        _await_ready(rec, cfg.get("ready_timeout_s", 300.0))
        self.coordinators.append(rec)
        return rec

    def drain_coordinator(self, i: int, timeout_s: float = 60.0) -> bool:
        """Scale the coordinator tier DOWN the polite way:
        ``PUT /v1/info/state SHUTTING_DOWN`` — the member sends its
        ``leaving`` farewell (peers drop its federated counts AND its
        peer-list entry immediately: explicit deregister, not the
        staleness grace), running queries page out, then the process
        exits. Never a kill."""
        rec = self.coordinators[i]
        p = rec["proc"]
        if p.poll() is not None:
            return False
        req = urllib.request.Request(
            rec["url"] + "/v1/info/state", data=b'"SHUTTING_DOWN"',
            method="PUT",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                r.read()
        except OSError:
            return False
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                _get_json(rec["url"] + "/v1/info", timeout=2)
            except urllib.error.HTTPError:
                pass
            except OSError:
                break                  # socket refused: drained
            time.sleep(0.1)
        if p.stdin:
            try:
                p.stdin.close()
            except OSError:
                pass
        try:
            p.wait(timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            return False
        return True

    # -- coordinator_scaler duck (exec/autoscale.AutoscaleController) --------
    def scale_up(self, reason: str = "") -> bool:
        """Admission-bound: one more coordinator = one more set of
        hard-concurrency slots over the same shared worker pool."""
        self.add_coordinator()
        return True

    def scale_down(self, reason: str = "") -> bool:
        live = [i for i, c in enumerate(self.coordinators)
                if c["proc"].poll() is None]
        if len(live) <= 2:             # a fleet needs >= 2 members
            return False
        return self.drain_coordinator(live[-1])

    def kill_coordinator(self, i: int) -> None:
        """SIGKILL — the real chaos primitive: no drain, no farewell
        heartbeat; peers learn via the staleness grace, clients via
        transport errors (FleetClient fails over)."""
        p = self.coordinators[i]["proc"]
        if p.poll() is None:
            p.kill()
            p.wait(timeout=30)

    def stop(self) -> None:
        procs = ([c["proc"] for c in self.coordinators]
                 + [w["proc"] for w in self.workers])
        for p in procs:
            if p.poll() is None and p.stdin:
                try:
                    p.stdin.close()   # tether EOF → clean child stop
                except OSError:
                    pass
        deadline = time.monotonic() + 20
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1,
                                       deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=10)


def _spawn(argv: List[str]) -> subprocess.Popen:
    env = dict(os.environ)
    # children must not recurse into fleet mode or inherit pins that
    # redirect THEIR summaries
    for k in ("SERVING_COORDINATORS", "SERVING_OUT"):
        env.pop(k, None)
    return subprocess.Popen(
        [sys.executable, "-m", "tools.fleet"] + argv,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, start_new_session=True)


def _await_ready(rec: dict, timeout_s: float) -> None:
    """Read the child's one-line ready doc (emitted after JAX import +
    data generation — the slow part), enforcing a wall deadline."""
    p = rec["proc"]

    def alarm(signum, frame):
        raise TimeoutError(
            f"fleet child {rec['node_id']} not ready in {timeout_s}s")

    old = signal.signal(signal.SIGALRM, alarm)
    signal.alarm(int(timeout_s))
    try:
        line = p.stdout.readline()
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
    if not line:
        raise RuntimeError(
            f"fleet child {rec['node_id']} died before ready "
            f"(rc={p.poll()})")
    doc = json.loads(line)
    assert doc.get("ok"), doc
    rec["url"] = doc["url"]


def launch_fleet(n_coordinators: int = 3, sf: float = 0.01,
                 workers: int = 1, sqlite_path: Optional[str] = None,
                 heartbeat_s: float = 0.5,
                 staleness_grace_s: Optional[float] = None,
                 groups: Optional[dict] = None,
                 ready_timeout_s: float = 300.0) -> FleetHandle:
    """Spawn the fleet: ``n_coordinators`` statement servers (each a
    fleet member, peered all-to-all) and ``workers`` worker processes
    announcing to every coordinator. Blocks until every child printed
    its ready line."""
    if n_coordinators < 2:
        raise ValueError("a fleet needs >= 2 coordinators")
    ports = _free_ports(n_coordinators + workers)
    coord_ports = ports[:n_coordinators]
    urls = [f"http://127.0.0.1:{p}" for p in coord_ports]
    coords: List[dict] = []
    for i, port in enumerate(coord_ports):
        node_id = f"coord-{i}"
        peers = ",".join(u for j, u in enumerate(urls) if j != i)
        argv = ["--serve-coordinator", "--port", str(port),
                "--node-id", node_id, "--peers", peers,
                "--sf", str(sf), "--heartbeat-s", str(heartbeat_s)]
        if sqlite_path:
            argv += ["--sqlite", sqlite_path]
        if staleness_grace_s:
            argv += ["--staleness-grace-s", str(staleness_grace_s)]
        if groups:
            argv += ["--groups-json", json.dumps(groups)]
        coords.append({"proc": _spawn(argv), "node_id": node_id,
                       "port": port, "url": f"http://127.0.0.1:{port}"})
    wrecs: List[dict] = []
    for i, port in enumerate(ports[n_coordinators:]):
        node_id = f"fleet-worker-{i}"
        argv = ["--serve-worker", "--port", str(port),
                "--node-id", node_id,
                "--coordinators", ",".join(urls), "--sf", str(sf)]
        if sqlite_path:
            argv += ["--sqlite", sqlite_path]
        wrecs.append({"proc": _spawn(argv), "node_id": node_id,
                      "port": port, "url": f"http://127.0.0.1:{port}"})
    handle = FleetHandle(
        coords, wrecs, sqlite_path,
        spawn_cfg={"sf": sf, "heartbeat_s": heartbeat_s,
                   "staleness_grace_s": staleness_grace_s,
                   "groups": groups,
                   "ready_timeout_s": ready_timeout_s})
    try:
        for rec in coords + wrecs:
            _await_ready(rec, ready_timeout_s)
        # a coordinator with ZERO visible workers fails SELECTs
        # ("no active workers") — hold the ready barrier until every
        # coordinator's discovery has the full worker pool
        deadline = time.monotonic() + ready_timeout_s
        for i in range(len(coords)):
            while True:
                seen = len(handle.fleet_status(i).get("workers", ()))
                if seen >= workers:
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"coordinator {coords[i]['node_id']} sees "
                        f"{seen}/{workers} workers")
                time.sleep(0.1)
    except BaseException:
        handle.stop()
        raise
    return handle


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools.fleet", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--serve-coordinator", action="store_true")
    ap.add_argument("--serve-worker", action="store_true")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--node-id", default="")
    ap.add_argument("--peers", default="")
    ap.add_argument("--coordinators", default="")
    ap.add_argument("--sf", type=float, default=0.01)
    ap.add_argument("--sqlite", default="")
    ap.add_argument("--heartbeat-s", type=float, default=0.5)
    ap.add_argument("--staleness-grace-s", type=float, default=0.0)
    ap.add_argument("--groups-json", default="")
    args = ap.parse_args(argv)
    if args.serve_coordinator:
        serve_coordinator(args)
        return 0
    if args.serve_worker:
        serve_worker(args)
        return 0
    ap.error("pick one of --serve-coordinator / --serve-worker")
    return 2


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Human verdict + schema gate for the SLO block in a SERVING pin.

Serving rounds from r03 on (``SERVING_OUT=path python bench.py
serving``) carry an ``slo`` block on the headline record: the
per-resource-group objectives the bench declared (``latency`` /
``availability``), the burn rates and error-budget remainder the
tracker (obs/slo.py) computed over the run, every alert transition it
fired, and the sampled burn timeline with the windowed p95 alongside.
This tool is how a serving PR proves the health plane still works:
render the block as a per-group verdict ("dash latency: OK, budget
100% left, worst burn 0.3x"), and schema-validate it so a re-pin that
dropped the timeline or fired an unexplained PAGE cannot be committed.

``check_bench_regression --kind serving`` imports
:func:`validate_slo_block` so the schema travels with the gate: in
``--smoke`` mode the pinned round itself must satisfy it, in run mode
the candidate must. Pins without an ``slo`` block (r02 and older)
pass vacuously — the gate never fails on history it cannot see.

Fleet pins (r04 on, ``SERVING_COORDINATORS>=2``) carry the MERGED
multi-coordinator form: a ``coordinators`` count plus a
``coordinator`` tag on every objective, alert and timeline row; the
windowed-p95 coverage check then applies per coordinator (every
member's sampler must have fed its own latency histogram).

Usage:
    python tools/slo_report.py                 # latest SERVING_r*.json
    python tools/slo_report.py SERVING_r03.json
    python tools/slo_report.py SERVING_r03.json --json report.json

Exit 0 when the pin's slo block passes the schema (or has none),
1 on violations, 2 on usage/IO errors.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: alert states, escalation order. Kept as a literal so the gate can
#: run without importing the engine; tests/test_slo.py asserts this
#: matches presto_tpu.obs.slo._RANK.
STATES = ("OK", "WARN", "PAGE")

#: alert rule names. tests/test_slo.py asserts this matches
#: presto_tpu.obs.slo.ALERT_RULES.
RULES = ("latency_burn", "availability_burn")

#: objective kinds a group may declare (server/resource_groups.py
#: ``_parse_slo``).
OBJECTIVES = ("latency", "availability")

#: schema of one slo block (bench.py ``_slo_block``)
_REQUIRED = ("sample_interval_s", "objectives", "alerts", "timeline")


def load_pin(path: str) -> Dict[str, Dict]:
    """{metric: record} from a SERVING pin: a committed ``_r*``
    wrapper (use its ``parsed``) or a bare ``SERVING_OUT`` summary."""
    with open(path) as f:
        doc = json.loads(f.read().strip())
    if isinstance(doc, dict) and "parsed" in doc:
        doc = doc["parsed"]
    out: Dict[str, Dict] = {}
    if not isinstance(doc, dict) or "metric" not in doc:
        raise ValueError(f"{path}: not a SERVING summary")
    out[doc["metric"]] = {k: v for k, v in doc.items()
                          if k != "sub_metrics"}
    for sub in doc.get("sub_metrics") or ():
        if isinstance(sub, dict) and "metric" in sub:
            out[sub["metric"]] = sub
    return out


def latest_pin(root: str = _REPO) -> Optional[str]:
    """Highest-numbered SERVING_r*.json — the pinned serving axis."""
    best, best_n = None, -1
    for p in glob.glob(os.path.join(root, "SERVING_r*.json")):
        m = re.search(r"SERVING_r(\d+)\.json$", os.path.basename(p))
        if m and int(m.group(1)) > best_n:
            best, best_n = p, int(m.group(1))
    return best


def _num(v: object) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _check_block(metric: str, slo: object,
                 violations: List[Dict]) -> None:
    """Schema checks for ONE slo block; appends any violations (each
    ``{"metric", "kind", "detail"}``)."""

    def bad(kind: str, detail: str) -> None:
        violations.append({"metric": metric, "kind": kind,
                           "detail": detail})

    if not isinstance(slo, dict):
        return bad("schema", "slo is not an object")
    missing = [k for k in _REQUIRED if k not in slo]
    if missing:
        return bad("schema", f"missing keys: {', '.join(missing)}")
    if not _num(slo["sample_interval_s"]) or \
            slo["sample_interval_s"] <= 0:
        bad("schema", "sample_interval_s must be a positive number")

    # fleet pins (r04 on) merge per-coordinator blocks into one:
    # ``coordinators`` counts the fleet and EVERY objective/alert/
    # timeline row must say which coordinator it came from, or the
    # merged block could silently collapse to one member's view
    fleet = slo.get("coordinators")
    if fleet is not None and (isinstance(fleet, bool)
                              or not isinstance(fleet, int)
                              or fleet < 2):
        bad("schema", "coordinators must be an int >= 2")
        fleet = None

    def coord_of(row: dict, where: str):
        if fleet is None:
            return None
        c = row.get("coordinator")
        if not isinstance(c, str) or not c:
            bad("schema", f"{where}: multi-coordinator block rows "
                          "need a non-empty coordinator tag")
            return None
        return c

    objectives = slo["objectives"]
    if not isinstance(objectives, list) or not objectives:
        return bad("schema", "objectives must be a non-empty list")
    latency_keys = set()
    for i, obj in enumerate(objectives):
        if not isinstance(obj, dict):
            bad("schema", f"objectives[{i}] is not an object")
            continue
        where = f"objectives[{i}]"
        if not isinstance(obj.get("group"), str) or not obj.get("group"):
            bad("schema", f"{where}: group must be a non-empty string")
        if obj.get("objective") not in OBJECTIVES:
            bad("schema", f"{where}: objective "
                          f"{obj.get('objective')!r} is not one of "
                          f"{'/'.join(OBJECTIVES)}")
        if not _num(obj.get("target")) or \
                not (0.0 < obj["target"] < 1.0):
            bad("schema", f"{where}: target must be in (0, 1)")
        coord = coord_of(obj, where)
        if obj.get("objective") == "latency":
            latency_keys.add((coord, obj.get("group"), "latency"))
            if not _num(obj.get("threshold_ms")) or \
                    obj["threshold_ms"] <= 0:
                bad("schema", f"{where}: latency objective needs a "
                              "positive threshold_ms")
        if obj.get("state") not in STATES:
            bad("schema", f"{where}: state {obj.get('state')!r} is "
                          f"not one of {'/'.join(STATES)}")
        for burn_key in ("burn_short", "burn_long"):
            b = obj.get(burn_key)
            if b is not None and (not _num(b) or b < 0):
                bad("schema", f"{where}: {burn_key} must be None or "
                              "a non-negative number")
        budget = obj.get("budget_remaining")
        if budget is not None and \
                (not _num(budget) or not (0.0 <= budget <= 1.0)):
            bad("schema", f"{where}: budget_remaining must be None "
                          "or in [0, 1]")

    alerts = slo["alerts"]
    if not isinstance(alerts, list):
        bad("schema", "alerts must be a list")
        alerts = []
    for i, a in enumerate(alerts):
        where = f"alerts[{i}]"
        if not isinstance(a, dict):
            bad("schema", f"{where} is not an object")
            continue
        coord_of(a, where)
        if not _num(a.get("ts")):
            bad("schema", f"{where}: ts must be a number")
        if a.get("rule") not in RULES:
            bad("schema", f"{where}: rule {a.get('rule')!r} is not "
                          f"one of {'/'.join(RULES)}")
        for side in ("from", "to"):
            if a.get(side) not in STATES:
                bad("schema", f"{where}: {side} state "
                              f"{a.get(side)!r} is not one of "
                              f"{'/'.join(STATES)}")

    timeline = slo["timeline"]
    if not isinstance(timeline, list) or not timeline:
        return bad("schema", "timeline must be a non-empty list "
                             "(the burn timeline is the point)")
    seen_p95 = set()
    for i, pt in enumerate(timeline):
        where = f"timeline[{i}]"
        if not isinstance(pt, dict):
            bad("schema", f"{where} is not an object")
            continue
        if not _num(pt.get("t")):
            bad("schema", f"{where}: t must be a number")
        if not isinstance(pt.get("group"), str) or \
                pt.get("objective") not in OBJECTIVES:
            bad("schema", f"{where}: needs group + objective")
        if pt.get("state") not in STATES:
            bad("schema", f"{where}: state {pt.get('state')!r} is "
                          f"not one of {'/'.join(STATES)}")
        b = pt.get("burn")
        if b is not None and (not _num(b) or b < 0):
            bad("schema", f"{where}: burn must be None or a "
                          "non-negative number")
        coord = coord_of(pt, where)
        p95 = pt.get("p95_ms")
        if p95 is not None:
            if not _num(p95) or p95 < 0:
                bad("schema", f"{where}: p95_ms must be a "
                              "non-negative number")
            else:
                seen_p95.add((coord, pt.get("group"),
                              pt.get("objective")))
    # the windowed p95 is what makes the latency timeline actionable;
    # a latency objective whose timeline never carries one means the
    # sampler never saw the histogram — a broken pin, not a quiet one.
    # In a merged fleet block the coverage is PER COORDINATOR: every
    # member's sampler must have seen its own histogram
    for coord, group, objective in sorted(
            latency_keys, key=lambda k: (k[0] or "", k[1], k[2])):
        if (coord, group, objective) not in seen_p95:
            who = f" on coordinator {coord!r}" if coord else ""
            bad("schema", f"latency objective for group {group!r}"
                          f"{who} has no timeline point with a "
                          "windowed p95_ms")


def validate_slo_block(flat: Dict[str, Dict]) -> Dict:
    """Schema-validate every slo block in a flattened pin. Pins
    without any block pass vacuously (pre-r03 history). Returns
    ``{"blocks", "violations", "ok"}``."""
    violations: List[Dict] = []
    blocks = 0
    for metric in sorted(flat):
        slo = flat[metric].get("slo")
        if slo is None:
            continue
        blocks += 1
        _check_block(metric, slo, violations)
    return {"blocks": blocks, "violations": violations,
            "ok": not violations}


def render(flat: Dict[str, Dict], verdict: Dict) -> str:
    """Human verdict: one line per objective, then the alert log."""
    lines: List[str] = []
    for metric in sorted(flat):
        slo = flat[metric].get("slo")
        if not isinstance(slo, dict):
            continue
        fleet = slo.get("coordinators")
        fleet_s = f", merged over {fleet} coordinators" \
            if isinstance(fleet, int) and not isinstance(fleet, bool) \
            else ""
        lines.append(f"{metric}: slo block "
                     f"(sampled every "
                     f"{slo.get('sample_interval_s')}s{fleet_s})")
        for obj in slo.get("objectives") or ():
            if not isinstance(obj, dict):
                continue
            burns = [b for b in (obj.get("burn_short"),
                                 obj.get("burn_long")) if b is not None]
            worst = f"worst burn {max(burns):.2f}x" if burns \
                else "no burn data"
            budget = obj.get("budget_remaining")
            budget_s = f"{budget * 100.0:.0f}% budget left" \
                if budget is not None else "budget unknown"
            thr = obj.get("threshold_ms")
            target = obj.get("target")
            detail = f"p{target * 100:g} < {thr:g}ms" \
                if obj.get("objective") == "latency" and \
                _num(thr) and _num(target) \
                else f"target {target}"
            c = obj.get("coordinator")
            gname = f"{c}:{obj.get('group')}" if c \
                else obj.get("group")
            lines.append(f"  {gname}/"
                         f"{obj.get('objective')} ({detail}): "
                         f"{obj.get('state')}, {budget_s}, {worst}")
        alerts = slo.get("alerts") or ()
        if alerts:
            lines.append(f"  {len(alerts)} alert transition(s):")
            for a in alerts:
                if isinstance(a, dict):
                    lines.append(f"    {a.get('group')}/"
                                 f"{a.get('objective')} "
                                 f"{a.get('from')} -> {a.get('to')} "
                                 f"({a.get('rule')})")
        else:
            lines.append("  no alert transitions")
    if not verdict["blocks"]:
        lines.append("no slo block (pre-r03 pin) — vacuous pass")
    for v in verdict["violations"]:
        lines.append(f"VIOLATION [{v['metric']}] {v['kind']}: "
                     f"{v['detail']}")
    lines.append(f"verdict: {'ok' if verdict['ok'] else 'FAIL'} "
                 f"({verdict['blocks']} block(s), "
                 f"{len(verdict['violations'])} violation(s))")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render + schema-check the slo block of a "
                    "SERVING pin")
    ap.add_argument("pin", nargs="?", default=None,
                    help="SERVING pin (default: latest "
                         "SERVING_r*.json)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="also write the verdict JSON to this file")
    args = ap.parse_args(argv)

    path = args.pin or latest_pin()
    if path is None or not os.path.exists(path):
        print("no SERVING_r*.json pin found", file=sys.stderr)
        return 2
    try:
        flat = load_pin(path)
    except (OSError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2

    verdict = validate_slo_block(flat)
    verdict["pin"] = path
    print(render(flat, verdict))
    if args.json:
        with open(args.json, "w") as f:
            f.write(json.dumps(verdict, indent=2) + "\n")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Reference consumer of the autoscaler signals feed — thin shim.

The policy used to live here; it is now the ONE rule registry in
``presto_tpu/exec/autoscale.py`` (:data:`RULES` / :func:`decide`),
shared verbatim with the real :class:`AutoscaleController` so the
reference watcher and the controller cannot drift
(tests/test_autoscale.py pins the parity: ``watch.decide is
autoscale.decide``). This tool keeps its CLI: print what the rules
recommend for one snapshot, decide nothing, provision nothing.

Usage:
    python tools/autoscale_watch.py          # snapshot this process
    python tools/autoscale_watch.py --demo   # synthetic busy cluster

Exit 0 always (a recommendation is not a failure).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from presto_tpu.exec.autoscale import (  # noqa: E402,F401
    RULES, decide, demo_signals)
from presto_tpu.obs.signals import cluster_signals  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="threshold watcher over the signals feed")
    ap.add_argument("--demo", action="store_true",
                    help="run against a synthetic busy cluster "
                         "instead of this process's live registries")
    args = ap.parse_args(argv)

    signals = demo_signals() if args.demo else cluster_signals()
    decisions = decide(signals)
    print(json.dumps({"ts": signals.ts,
                      "groups": len(signals.groups),
                      "nodes": len(signals.nodes),
                      "decisions": decisions}, indent=2))
    if not decisions:
        print("no action recommended", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

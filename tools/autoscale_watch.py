#!/usr/bin/env python
"""Reference consumer of the autoscaler signals feed (obs/signals.py).

``cluster_signals()`` is a frozen, read-only snapshot — it decides
nothing. This tool is the demo policy that proves the feed carries
enough to act on: a pure function :func:`decide` maps one
``ClusterSignals`` snapshot to a list of recommendations
(``scale_up`` / ``scale_down`` / ``replace_node`` / ``grow_cache``),
each with the signal values that justified it. A real autoscaler
would swap the thresholds and actually provision; the contract — what
fields exist and what they mean — is exactly what this file consumes,
and tests/test_slo.py drives it in-suite so a feed change that breaks
a consumer fails tier-1.

Policy (deliberately boring thresholds, all keyword-overridable):

- ``scale_up`` a group when its queue backs up past
  ``queue_ratio`` x the hard concurrency limit, or when its SLO alert
  has escalated to PAGE (burning budget 10x+ over plan: more
  replicas, not more patience);
- ``scale_down`` a group only when it is quiet (no queue, running
  below ``idle_ratio`` of the limit), its alert is OK, and its error
  budget is healthy — a WARN holds scale-down, shrinking a burning
  group digs the hole deeper;
- ``replace_node`` when a node's heartbeat is older than
  ``stale_heartbeat_s`` (the registry's own liveness signal);
- ``grow_cache`` when any serving cache's fill fraction exceeds
  ``cache_pressure`` — cache evictions surface as latency burn one
  window later, so pressure is the leading indicator.

Usage:
    python tools/autoscale_watch.py          # snapshot this process
    python tools/autoscale_watch.py --demo   # synthetic busy cluster

Exit 0 always (a recommendation is not a failure).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from presto_tpu.obs.signals import (  # noqa: E402
    CacheSignals, ClusterSignals, GroupSignals, NodeSignals,
    cluster_signals)


def decide(signals: ClusterSignals, *,
           queue_ratio: float = 2.0,
           idle_ratio: float = 0.25,
           stale_heartbeat_s: float = 30.0,
           cache_pressure: float = 0.9,
           min_budget: float = 0.5) -> List[Dict]:
    """Map one frozen snapshot to scaling recommendations.

    Pure and deterministic: same snapshot, same decisions. Each entry
    is ``{"action", "target", "reason", "signals": {...}}`` with the
    raw values the rule fired on, so the operator (or a test) can
    audit the decision against the feed."""
    out: List[Dict] = []
    for g in signals.groups:
        limit = max(1, g.hard_concurrency_limit)
        if g.queued >= queue_ratio * limit or g.alert_state == "PAGE":
            why = (f"alert {g.alert_state}" if g.alert_state == "PAGE"
                   else f"queue {g.queued} >= {queue_ratio:g}x "
                        f"limit {limit}")
            out.append({"action": "scale_up", "target": g.group,
                        "reason": why,
                        "signals": {"queued": g.queued,
                                    "running": g.running,
                                    "limit": limit,
                                    "alert_state": g.alert_state,
                                    "burn_short": g.burn_short,
                                    "p95_s": g.p95_s}})
        elif (g.queued == 0 and g.running < idle_ratio * limit
              and g.alert_state == "OK"
              and (g.error_budget_remaining is None
                   or g.error_budget_remaining >= min_budget)):
            out.append({"action": "scale_down", "target": g.group,
                        "reason": f"idle: running {g.running} < "
                                  f"{idle_ratio:g}x limit {limit}, "
                                  "no queue, alert OK",
                        "signals": {"running": g.running,
                                    "limit": limit,
                                    "budget":
                                        g.error_budget_remaining}})
    for n in signals.nodes:
        if n.heartbeat_age_s > stale_heartbeat_s:
            out.append({"action": "replace_node", "target": n.node_id,
                        "reason": f"heartbeat {n.heartbeat_age_s:.1f}s"
                                  f" > {stale_heartbeat_s:g}s stale "
                                  "threshold",
                        "signals": {"state": n.state,
                                    "heartbeat_age_s":
                                        n.heartbeat_age_s}})
    caches = signals.caches
    for name, pressure in (("scan", caches.scan_cache_pressure),
                           ("plan", caches.plan_cache_pressure),
                           ("result", caches.result_cache_pressure)):
        if pressure > cache_pressure:
            out.append({"action": "grow_cache",
                        "target": f"{name}_cache",
                        "reason": f"fill {pressure:.0%} > "
                                  f"{cache_pressure:.0%} pressure "
                                  "threshold",
                        "signals": {"pressure": round(pressure, 4)}})
    return out


def demo_signals() -> ClusterSignals:
    """A synthetic busy cluster exercising every rule: one backed-up
    group, one paging group, one idle group, one stale node, one hot
    cache."""
    return ClusterSignals(
        ts=0.0,
        groups=(
            GroupSignals(group="serving.dash", state="FULL",
                         running=8, queued=20,
                         hard_concurrency_limit=8,
                         p95_s=0.45, burn_short=1.2, burn_long=0.8,
                         error_budget_remaining=0.6,
                         alert_state="OK"),
            GroupSignals(group="serving.adhoc", state="CAN_RUN",
                         running=3, queued=1,
                         hard_concurrency_limit=8,
                         p95_s=2.1, burn_short=14.0, burn_long=11.0,
                         error_budget_remaining=0.0,
                         alert_state="PAGE"),
            GroupSignals(group="batch", state="CAN_RUN",
                         running=0, queued=0,
                         hard_concurrency_limit=16,
                         error_budget_remaining=1.0,
                         alert_state="OK"),
        ),
        nodes=(
            NodeSignals(node_id="w0", state="active",
                        heartbeat_age_s=1.5, active_tasks=4),
            NodeSignals(node_id="w1", state="active",
                        heartbeat_age_s=95.0, active_tasks=0),
        ),
        caches=CacheSignals(scan_cache_resident_bytes=950,
                            scan_cache_limit_bytes=1000,
                            plan_cache_entries=10,
                            plan_cache_capacity=64,
                            result_cache_resident_bytes=100,
                            result_cache_limit_bytes=1000),
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="threshold watcher over the signals feed")
    ap.add_argument("--demo", action="store_true",
                    help="run against a synthetic busy cluster "
                         "instead of this process's live registries")
    args = ap.parse_args(argv)

    signals = demo_signals() if args.demo else cluster_signals()
    decisions = decide(signals)
    print(json.dumps({"ts": signals.ts,
                      "groups": len(signals.groups),
                      "nodes": len(signals.nodes),
                      "decisions": decisions}, indent=2))
    if not decisions:
        print("no action recommended", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Bucket-level diff of two MULTICHIP pins + per-bucket overhead budgets.

The MULTICHIP pins (``MULTICHIP_OUT=path python bench.py multichip``)
carry, on every n>1 rows/s record, the flight recorder's ``attribution``
block (obs/flight.py): wall-clock seconds per bucket
(``device_compute`` / ``dispatch_overhead`` / ``host_staging`` /
``control_sync`` / ``repartition`` / ``stall``), the dominant bucket,
the reconciled fraction, and the per-shard critical path. This tool is
how a mesh perf PR proves its claim: diff the NEW pin against the OLD
one bucket-by-bucket, so "q1sql n4 got 1.3x faster" decomposes into
"repartition dropped 800ms, dispatch unchanged" instead of a bare
rows/s delta.

It also owns the per-bucket **overhead budgets** — the declared maximum
share of query wall each overhead bucket may consume on the pinned
multichip axis. ``check_bench_regression --kind multichip`` imports
:func:`validate_attribution` so the budgets gate every re-pin: an
exchange refactor that silently doubles control-sync wall fails the
gate even if rows/s noise hides it.

Usage:
    python tools/mesh_report.py MULTICHIP_r06.json MULTICHIP_r07.json
    python tools/mesh_report.py OLD NEW --json report.json

Pins from rounds before the flight recorder (r06 and older) carry no
attribution blocks: the diff for those metrics is reported as
``no attribution`` and the budgets pass vacuously — the tool never
fails on history it cannot see.

Exit 0 when the NEW pin's attribution passes schema + budgets (or has
none), 1 on violations, 2 on usage/IO errors.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: attribution bucket names, display order. Kept as a literal so the
#: gate can run without importing the engine; tests/test_mesh_flight.py
#: asserts this matches presto_tpu.obs.flight.BUCKETS.
BUCKETS = ("device_compute", "dispatch_overhead", "host_staging",
           "control_sync", "repartition", "stall")

#: per-bucket budget: max share of query wall (percent) an overhead
#: bucket may consume on the pinned multichip axis. ``device_compute``
#: is the useful work — never budgeted. ``dispatch_overhead`` on the
#: forced-CPU pin still CONTAINS whatever device compute the backend's
#: queue forces a dispatch call to absorb (see obs/flight.py), so its
#: budget stays high — but the fused-exchange overhaul (r08) capped it
#: at 90: the r08 worst case is 84.5% (q27 n8, serialized join compute
#: on the 1-core virtual mesh), and a future PR that reintroduces
#: per-round host dispatch would push past it. ``control_sync`` is the
#: bucket with real teeth now: the fused control plane plus the
#: input-drain bracket at every sync site (``_drain_inputs``) left the
#: r08 maximum at 6.1% of wall, so 25% catches any control-plane
#: regression with margin for a TPU re-pin's slower scalar readbacks.
BUCKET_BUDGET_PCT: Dict[str, float] = {
    "dispatch_overhead": 90.0,
    "host_staging": 80.0,
    "control_sync": 25.0,
    "repartition": 85.0,
    "stall": 60.0,
}

#: schema of one attribution block (obs/flight.FlightRecorder.finish)
_REQUIRED = ("query_id", "n_devices", "wall_s", "rounds", "buckets",
             "dominant_bucket", "reconciled_pct", "overhead_s",
             "critical_path")


def load_pin(path: str) -> Dict[str, Dict]:
    """{metric: record} from a MULTICHIP pin: a committed ``_r*``
    wrapper (use its ``parsed``) or a bare ``MULTICHIP_OUT`` summary."""
    with open(path) as f:
        doc = json.loads(f.read().strip())
    if isinstance(doc, dict) and "parsed" in doc:
        doc = doc["parsed"]
    out: Dict[str, Dict] = {}
    if not isinstance(doc, dict) or "metric" not in doc:
        raise ValueError(f"{path}: not a MULTICHIP summary")
    out[doc["metric"]] = {k: v for k, v in doc.items()
                          if k != "sub_metrics"}
    for sub in doc.get("sub_metrics") or ():
        if isinstance(sub, dict) and "metric" in sub:
            out[sub["metric"]] = sub
    return out


def _check_block(metric: str, attr: object,
                 violations: List[Dict]) -> None:
    """Schema + budget checks for ONE attribution block; appends any
    violations (each ``{"metric", "kind", "detail"}``)."""

    def bad(kind: str, detail: str) -> None:
        violations.append({"metric": metric, "kind": kind,
                           "detail": detail})

    if not isinstance(attr, dict):
        return bad("schema", "attribution is not an object")
    missing = [k for k in _REQUIRED if k not in attr]
    if missing:
        return bad("schema", f"missing keys: {', '.join(missing)}")
    buckets = attr["buckets"]
    if not isinstance(buckets, dict) or \
            sorted(buckets) != sorted(BUCKETS):
        return bad("schema", "buckets must carry exactly "
                             f"{'/'.join(BUCKETS)}")
    for b, s in buckets.items():
        if not isinstance(s, (int, float)) or s < 0:
            return bad("schema", f"bucket {b} is not a "
                                 "non-negative number")
    if attr["dominant_bucket"] not in BUCKETS:
        bad("schema", f"dominant_bucket {attr['dominant_bucket']!r} "
                      "is not a bucket")
    wall = float(attr["wall_s"] or 0.0)
    if wall <= 0:
        return bad("schema", "wall_s must be positive")
    cp = attr["critical_path"]
    if not isinstance(cp, dict) or \
            not isinstance(cp.get("per_shard_s"), list) or \
            len(cp["per_shard_s"]) != int(attr["n_devices"]):
        bad("schema", "critical_path.per_shard_s must list one entry "
                      "per device")
    for b, budget in BUCKET_BUDGET_PCT.items():
        share = float(buckets.get(b, 0.0)) / wall * 100.0
        if share > budget:
            bad("budget", f"{b} at {share:.1f}% of wall exceeds the "
                          f"{budget:g}% budget")


def validate_attribution(flat: Dict[str, Dict]) -> Dict:
    """Schema-validate + budget-check every attribution block in a
    flattened pin. Pins without any block pass vacuously (pre-r07
    history). Returns ``{"blocks", "violations", "ok"}``."""
    violations: List[Dict] = []
    blocks = 0
    for metric in sorted(flat):
        attr = flat[metric].get("attribution")
        if attr is None:
            continue
        blocks += 1
        _check_block(metric, attr, violations)
    return {"blocks": blocks, "violations": violations,
            "ok": not violations}


def diff_pins(old: Dict[str, Dict], new: Dict[str, Dict]) -> List[Dict]:
    """Per-metric bucket deltas for metrics carrying attribution on
    either side. ``delta_s`` is new minus old (negative = the bucket
    got cheaper); sides without attribution diff as None."""
    rows: List[Dict] = []
    for metric in sorted(set(old) | set(new)):
        a_old = (old.get(metric) or {}).get("attribution")
        a_new = (new.get(metric) or {}).get("attribution")
        if a_old is None and a_new is None:
            continue
        row = {"metric": metric,
               "old_wall_s": a_old and a_old.get("wall_s"),
               "new_wall_s": a_new and a_new.get("wall_s"),
               "buckets": {}}
        for b in BUCKETS:
            o = a_old and float(a_old["buckets"].get(b, 0.0))
            n = a_new and float(a_new["buckets"].get(b, 0.0))
            row["buckets"][b] = {
                "old_s": o, "new_s": n,
                "delta_s": (round(n - o, 6)
                            if o is not None and n is not None
                            else None)}
        if a_new is not None:
            row["new_dominant"] = a_new.get("dominant_bucket")
            row["new_reconciled_pct"] = a_new.get("reconciled_pct")
        rows.append(row)
    return rows


def _fmt_s(v: Optional[float]) -> str:
    return "-" if v is None else f"{v * 1e3:9.1f}"


def format_report(rows: List[Dict], verdict: Dict,
                  old_path: str, new_path: str) -> str:
    """Human-readable bucket-delta tables, one per metric."""
    out = [f"mesh report: {os.path.basename(old_path)} -> "
           f"{os.path.basename(new_path)}"]
    if not rows:
        out.append("  no attribution blocks on either side "
                   "(pre-flight-recorder pins)")
    for row in rows:
        wall = (f"wall {_fmt_s(row['old_wall_s']).strip()}ms -> "
                f"{_fmt_s(row['new_wall_s']).strip()}ms")
        out.append(f"\n{row['metric']}  ({wall})")
        out.append(f"  {'bucket':<18} {'old_ms':>9} {'new_ms':>9} "
                   f"{'delta_ms':>9}")
        for b in BUCKETS:
            d = row["buckets"][b]
            delta = ("-" if d["delta_s"] is None
                     else f"{d['delta_s'] * 1e3:+9.1f}")
            out.append(f"  {b:<18} {_fmt_s(d['old_s'])} "
                       f"{_fmt_s(d['new_s'])} {delta:>9}")
        if "new_dominant" in row:
            out.append(f"  dominant: {row['new_dominant']}, "
                       f"{row['new_reconciled_pct']}% of wall "
                       "attributed")
    out.append(f"\nbudgets ({verdict['blocks']} attribution "
               f"block{'s' if verdict['blocks'] != 1 else ''}): "
               + ("PASS" if verdict["ok"] else "FAIL"))
    for v in verdict["violations"]:
        out.append(f"  {v['metric']}: [{v['kind']}] {v['detail']}")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two MULTICHIP pins bucket-by-bucket and "
                    "enforce per-bucket overhead budgets on the new "
                    "one")
    ap.add_argument("old", help="baseline pin (e.g. MULTICHIP_r06.json)")
    ap.add_argument("new", help="candidate pin (e.g. MULTICHIP_r07.json "
                                "or a fresh MULTICHIP_OUT file)")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="also write the machine-readable report here")
    args = ap.parse_args(argv)

    try:
        old = load_pin(args.old)
        new = load_pin(args.new)
    except (OSError, ValueError) as e:
        print(json.dumps({"verdict": "error", "error": str(e)}))
        return 2

    rows = diff_pins(old, new)
    verdict = validate_attribution(new)
    print(format_report(rows, verdict, args.old, args.new))
    if args.json:
        doc = {"old": args.old, "new": args.new, "diff": rows,
               "budgets": verdict,
               "verdict": "pass" if verdict["ok"] else "fail"}
        with open(args.json, "w") as f:
            f.write(json.dumps(doc, indent=2) + "\n")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
